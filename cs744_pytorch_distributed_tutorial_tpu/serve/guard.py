"""graftguard — deadlines, admission control, overload shedding, and
supervised engine auto-recovery for the serving stack.

The engine (``serve/engine.py``) assumes a well-behaved world: every
submitted request eventually decodes, the queue is unbounded, and the
only failure it survives is a cooperative kill/resume. This module adds
the production guardrails, all host-side so the fixed-shape decode step
never retraces (GL002):

- **Per-request deadlines** (``ServeGuard.expire``): ``deadline_s``
  bounds arrival→retire wall time, ``max_queue_s`` bounds time queued
  before first admission. Swept at the top of every ``step()`` —
  equivalently, checked at admission (an expired queue head is removed
  before refill) and per decode step (an expired active slot retires
  and its pages free immediately; ``PagePool.check_invariants`` audits
  the reclamation). Expired requests resolve terminally as
  ``timed_out`` — never silently dropped, never leaked.
- **Admission control + shedding** (``ServeGuard.admit``, called from
  ``submit()``): a bounded queue rejects at ``max_queue_depth``
  (status ``rejected``); policy ``"degrade"`` first trims
  ``max_new_tokens`` toward ``degrade_floor`` under pool pressure, so
  the engine sheds WORK before it sheds REQUESTS. Every shed emits a
  ``kind:"serve_shed"`` record with a machine-readable ``reason``
  (``queue_full`` / ``degrade_trim``). Because the per-request PRNG
  streams are keyed by (req_id, absolute token index), a degrade-
  trimmed request's output is a bitwise PREFIX of its untrimmed oracle
  output at any temperature.
- **Supervised auto-recovery** (``run_serve_with_recovery``): the serve
  mirror of ``utils/failure.py::run_with_recovery``. It drives a
  Poisson workload against the engine; a detected ``ServeFailure``
  (``DecodeNanError`` from poisoned logits, ``EngineCrashError`` from a
  dead step, ``HungStepError`` after the ``StepWatchdog`` climbs its
  warn→flight-dump→abort ladder) triggers: snapshot the dead engine's
  host state, exponential backoff, rebuild a fresh engine
  (``make_engine``), re-install the chaos monkey (its cumulative
  decode-step counter spans restarts, so popped faults never re-fire),
  ``resume()`` the snapshot, and continue the workload. In-flight
  requests replay token-identically (greedy bitwise; sampled via the
  per-request PRNG streams). Every transition emits ``recovery_*``
  events; a crash never surfaces to the client.

``docs/reliability.md`` ("Serving under failure and overload") is the
operator story; ``tests/test_serve_guard.py`` and the chaos-smoke CI
job pin all of it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.serve.engine import Request
from cs744_pytorch_distributed_tutorial_tpu.serve.loadgen import (
    _emit_summary,
    _summarize,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    DecodeNanError,
    EngineCrashError,
    HungStepError,
    ServeFailure,
    StepWatchdog,
    emit_event,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger

__all__ = [
    "GuardConfig",
    "ServeGuard",
    "run_serve_with_recovery",
    "ServeFailure",
    "DecodeNanError",
    "EngineCrashError",
    "HungStepError",
]


@dataclass
class GuardConfig:
    """Admission-control and SLO policy for a ``ServeGuard``.

    All knobs default to "off" (None) — an all-default guard is a
    no-op, so wiring one unconditionally costs nothing.
    """

    # Default per-request budgets; a request's own ``deadline_s`` /
    # ``max_queue_s`` fields (set by the client) win over these.
    deadline_s: float | None = None
    max_queue_s: float | None = None
    # Bounded queue: submissions beyond this depth shed. None = unbounded.
    max_queue_depth: int | None = None
    # "reject": over-bound submissions terminally reject.
    # "degrade": ALSO trim max_new_tokens toward ``degrade_floor`` when
    # the pool is under pressure — shed work before shedding requests.
    shed_policy: str = "reject"
    degrade_floor: int = 8
    # Pool pressure = free pages below this fraction of the allocatable
    # pool (num_pages - 1).
    pressure_free_frac: float = 0.25

    def __post_init__(self) -> None:
        if self.shed_policy not in ("reject", "degrade"):
            raise ValueError(
                f'shed_policy must be "reject" or "degrade", got '
                f"{self.shed_policy!r}"
            )
        if self.degrade_floor < 1:
            raise ValueError(
                f"degrade_floor must be >= 1, got {self.degrade_floor}"
            )
        if not (0.0 <= self.pressure_free_frac <= 1.0):
            raise ValueError(
                f"pressure_free_frac must be in [0, 1], got "
                f"{self.pressure_free_frac}"
            )


@dataclass
class ServeGuard:
    """Admission control + deadline enforcement over a ``ServingEngine``.

    Pass one as ``ServingEngine(..., guard=ServeGuard(cfg))``. The
    engine calls ``admit`` from ``submit()`` and ``expire`` at the top
    of every ``step()``; both operate purely on host state and the
    engine's injectable ``clock``, so guarded runs are deterministic
    under a fake clock and the jitted decode step is untouched.

    ``shed_counts`` accumulates shed events by reason (terminal rejects
    AND non-terminal degrade trims) for tests and summaries.
    """

    cfg: GuardConfig = field(default_factory=GuardConfig)
    shed_counts: dict[str, int] = field(default_factory=dict)
    timed_out: int = 0

    def _count(self, reason: str) -> None:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1

    # Called from ``ServingEngine.submit`` after id assignment, before
    # the capacity checks and the queue append.
    def admit(self, engine: Any, req: Request) -> bool:
        """Admission control for one submission. Returns False when the
        request was terminally shed (engine._shed_reject already ran);
        may mutate ``req`` (budget defaults, degrade trim) on the True
        path."""
        if req.recovered:
            # A resumed request was already admitted once (possibly on a
            # dead engine); shedding it now would break the recovery
            # contract that no admitted request is lost. Its budgets
            # came through the snapshot.
            return True
        cfg = self.cfg
        if req.deadline_s is None:
            req.deadline_s = cfg.deadline_s
        if req.max_queue_s is None:
            req.max_queue_s = cfg.max_queue_s
        if (
            cfg.max_queue_depth is not None
            and len(engine._queue) >= cfg.max_queue_depth
        ):
            self._count("queue_full")
            engine._shed_reject(
                req, "queue_full", queue_depth=len(engine._queue)
            )
            return False
        if cfg.shed_policy == "degrade":
            pool = engine.pool
            allocatable = pool.num_pages - 1
            pressured = pool.free_pages < cfg.pressure_free_frac * allocatable
            if pressured and req.max_new_tokens > cfg.degrade_floor:
                trimmed = int(req.max_new_tokens) - cfg.degrade_floor
                req.max_new_tokens = cfg.degrade_floor
                self._count("degrade_trim")
                engine._emit({
                    "kind": "serve_shed",
                    "time": time.time(),
                    "id": req.req_id,
                    "reason": "degrade_trim",
                    "terminal": False,
                    "tokens_shed": trimmed,
                    "free_pages": pool.free_pages,
                })
        return True

    # Called from the top of ``ServingEngine.step``.
    def expire(self, engine: Any) -> None:
        """Sweep queued and active requests against their budgets; every
        expiry resolves terminally as ``timed_out`` (queued requests
        just finish; active slots retire and free their pages)."""
        now = engine.clock()
        expired = [
            (r, self._expiry_reason(r, now, queued=True))
            for r in engine._queue
        ]
        for req, reason in expired:
            if reason is None:
                continue
            engine._queue.remove(req)
            self.timed_out += 1
            engine._expire_request(req, slot=None, reason=reason)
        for i, slot in enumerate(engine._slots):
            if slot is None:
                continue
            reason = self._expiry_reason(slot.req, now, queued=False)
            if reason is not None:
                self.timed_out += 1
                engine._expire_request(slot.req, slot=i, reason=reason)

    @staticmethod
    def _expiry_reason(
        req: Request, now: float, *, queued: bool
    ) -> str | None:
        if (
            req.deadline_s is not None
            and req.arrival_time is not None
            and now - req.arrival_time > req.deadline_s
        ):
            return "deadline"
        if (
            queued
            and req.max_queue_s is not None
            and req.first_token_time is None
            and now - req.submit_time > req.max_queue_s
        ):
            return "queue_wait"
        return None


def _merge_stats(total: dict[str, Any], part: dict[str, Any]) -> None:
    """Fold one engine generation's ``stats()`` into the running totals
    (sums for counters, max for high-water marks)."""
    for k, v in part.items():
        if k in ("page_high_water",):
            total[k] = max(total.get(k, 0), v)
        elif k in ("slot_occupancy", "pages_allocatable"):
            total[k] = v  # latest generation's view
        else:
            total[k] = total.get(k, 0) + v


def run_serve_with_recovery(
    make_engine: Callable[[], Any],
    workload: Any,
    *,
    monkey: Any = None,
    max_restarts: int = 2,
    backoff_s: float = 0.0,
    backoff_factor: float = 2.0,
    max_backoff_s: float = 60.0,
    sleep: Callable[[float], None] = time.sleep,
    step_timeout_s: float | None = None,
    telemetry: Any = None,
    sink: Any = None,
    warmup: bool = True,
    label: str = "continuous",
) -> dict[str, Any]:
    """Drive a Poisson ``Workload`` with supervised engine auto-recovery.

    The serving mirror of ``run_with_recovery``: the loop submits
    arrivals on the wall clock and steps the engine; a ``ServeFailure``
    — ``DecodeNanError`` (host-side token validation), ``EngineCrashError``
    (the step died), or ``HungStepError`` (the ``StepWatchdog``'s
    warn→dump→abort ladder exhausted on a wedged step) — triggers the
    restart ladder instead of surfacing to the client:

    1. ``recovery_restart`` event + exponential backoff
       (``backoff_s * backoff_factor**(n-1)``, capped at
       ``max_backoff_s``; ``sleep`` injectable),
    2. ``snapshot()`` the dead engine's host state (valid even after the
       crash — the engine raises before per-step bookkeeping mutates)
       and bank its completed requests,
    3. ``make_engine()`` a fresh engine, re-install ``monkey``
       (``ServeChaosMonkey`` — its cumulative decode-step counter spans
       restarts, so a popped fault never re-fires),
    4. ``resume()`` the snapshot: in-flight requests replay
       token-identically through the recompute path (greedy bitwise;
       sampled via the per-request PRNG streams),
    5. continue the workload where it left off.

    Past ``max_restarts`` the ladder gives up: ``recovery_giveup``
    (with the failure's full traceback string) and re-raise.

    ``step_timeout_s`` arms a per-engine ``StepWatchdog`` with the
    escalation ladder ``("warn", "dump", "abort")`` and the engine's
    flight recorder — a stalled decode step warns, dumps the flight
    tail, then (via the abort stage) marks the step hung; when the
    step finally returns the supervisor raises ``HungStepError`` into
    the ladder above. The first engine warms up its prefill buckets
    before the clock starts (as ``run_poisson`` does); replacement
    engines compile inline — that recompilation IS the recovery
    downtime and is honestly on the clock.

    Returns the ``serve_summary`` record (aggregated across engine
    generations, ``restarts`` included), emitted on ``sink`` with the
    same bench twins ``run_poisson`` emits.
    """
    log = get_logger()
    engine = make_engine()

    if warmup:
        # Same discipline as run_poisson: compile the decode step and
        # the prefill buckets this workload will touch, off the clock,
        # with sink/tracer/guard detached so warmup traffic never lands
        # in telemetry or admission counters. The monkey installs AFTER
        # warmup, so fault-schedule indices count MEASURED decode steps
        # only — index k means "the k-th live decode step", warmup or
        # not.
        saved = (engine.sink, engine.tracer, engine.guard)
        engine.sink = engine.tracer = engine.guard = None
        buckets = sorted({
            engine._bucket_for(len(p)) for p in workload.prompts
        })
        for b in buckets:
            # budget 2, not 1: the second token forces a decode step, so
            # the decode executable compiles off the clock too.
            engine.submit(Request(
                prompt=np.ones((min(b, engine.max_seq_len - 2),), np.int32),
                max_new_tokens=2,
            ))
        while engine.busy:
            engine.step()
        engine._completed.clear()
        engine._preemptions = 0
        engine._timed_out = 0
        engine._shed = 0
        engine._step_count = 0
        engine._active_slot_steps = 0
        engine._trash_rows = 0
        engine._decode_walls.clear()
        engine._event_ring.clear()
        engine.pool.high_water = 0
        engine.pool.total_allocs = 0
        engine.pool.total_frees = 0
        engine._next_id = 0
        engine.sink, engine.tracer, engine.guard = saved
        if engine.tracer is not None:
            engine.tracer.reset(engine.clock())

    if monkey is not None:
        monkey.install(engine)

    def _make_watchdog(eng: Any) -> tuple[Any, dict[str, bool]]:
        if step_timeout_s is None:
            return None, {"flag": False}
        hung = {"flag": False}

        def on_hang(elapsed_s: float) -> None:
            hung["flag"] = True

        wd = StepWatchdog(
            step_timeout_s,
            on_hang=on_hang,
            escalation=("warn", "dump", "abort"),
            flight_recorder=eng.make_flight_recorder(),
        )
        return wd, hung

    wd, hung = _make_watchdog(engine)
    totals: dict[str, Any] = {}
    finished: list[Request] = []
    restarts = 0
    prev_restarts = 0
    arrivals = workload.arrivals
    n = len(arrivals)
    i = 0
    t0 = engine.clock()
    try:
        while i < n or engine.busy:
            now = engine.clock() - t0
            while i < n and arrivals[i] <= now:
                engine.submit(Request(
                    prompt=workload.prompts[i],
                    max_new_tokens=int(workload.max_new_tokens[i]),
                    arrival_time=t0 + float(arrivals[i]),
                ))
                i += 1
            if not engine.busy:
                if i < n:
                    time.sleep(
                        min(0.001, max(0.0, float(arrivals[i]) - now))
                    )
                continue
            try:
                if wd is not None:
                    with wd.watch():
                        engine.step()
                else:
                    engine.step()
                if hung["flag"]:
                    hung["flag"] = False
                    raise HungStepError(elapsed_s=step_timeout_s or 0.0)
            except ServeFailure as e:
                restarts += 1
                if restarts > max_restarts:
                    import traceback as _tb

                    emit_event(
                        telemetry,
                        "recovery_giveup",
                        restarts=restarts - 1,
                        failure=repr(e),
                        traceback="".join(_tb.format_exception(e)),
                    )
                    log.critical(
                        "serve recovery giving up after %d restarts "
                        "(last failure: %s)", restarts - 1, e,
                    )
                    raise
                delay = 0.0
                if backoff_s > 0:
                    delay = min(
                        backoff_s * backoff_factor ** (restarts - 1),
                        max_backoff_s,
                    )
                emit_event(
                    telemetry,
                    "recovery_restart",
                    restart=restarts,
                    max_restarts=max_restarts,
                    failure=repr(e),
                    tier="engine",
                    backoff_s=delay,
                )
                log.error(
                    "serve failure (%s); engine restart %d/%d "
                    "(backoff %.1fs)", e, restarts, max_restarts, delay,
                )
                # The dead engine's host state is snapshot-consistent:
                # every ServeFailure raises before per-step bookkeeping.
                snap = engine.snapshot()
                if engine.tracer is not None:
                    # The tracer outlives the generation: seal its open
                    # spans at the crash instant so the next
                    # generation's spans never overlap them.
                    engine.tracer.on_crash(engine.clock())
                finished.extend(engine._completed)
                _merge_stats(totals, engine.stats())
                if wd is not None:
                    wd.close()
                if delay > 0:
                    sleep(delay)
                engine = make_engine()
                if monkey is not None:
                    monkey.install(engine)
                engine.resume(snap)
                wd, hung = _make_watchdog(engine)
    finally:
        if wd is not None:
            wd.close()
    if restarts > prev_restarts:
        emit_event(telemetry, "recovery_complete", restarts=restarts)
    engine.finalize_trace()
    reqs = finished + list(engine._completed)
    _merge_stats(totals, engine.stats())
    totals["requests_done"] = len(reqs)
    totals["restarts"] = restarts
    # Terminal accounting: every submitted request must have resolved to
    # exactly one terminal status — nothing unresolved, nothing doubled.
    ids = sorted(r.req_id for r in reqs)
    assert ids == sorted(set(ids)), (
        f"requests resolved more than once: "
        f"{sorted({x for x in ids if ids.count(x) > 1})}"
    )
    unresolved = [r.req_id for r in reqs if r.terminal_status is None]
    assert not unresolved, f"requests ended unresolved: {unresolved}"
    assert len(ids) == n, (
        f"submitted {n} requests but only {len(ids)} resolved"
    )
    makespan = max(
        (r.done_time for r in reqs if r.done_time is not None),
        default=t0,
    ) - t0
    record = _summarize(label, reqs, makespan, totals)
    _emit_summary(sink, record)
    return record
