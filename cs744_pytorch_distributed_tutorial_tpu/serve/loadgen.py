"""Poisson load generation and the batch-at-a-time baseline.

``make_poisson_workload`` draws a seeded open-loop trace (exponential
inter-arrivals, uniform prompt/output lengths); ``run_poisson`` replays
it against a ``ServingEngine`` on the wall clock and reports the serving
metrics the ISSUE names:

- **TTFT** (time to first token): first sampled token's host arrival
  minus the request's scheduled arrival — it INCLUDES queue time, which
  is the point (tail TTFT is where batch-at-a-time loses).
- **per-token decode latency**: (done - first token) / (output - 1).
- **ITL** (inter-token latency): gaps between consecutive streamed
  token deliveries (``Request.token_times``, populated by the engine's
  per-token surfacing) — the tail a streaming client sees, including
  prefill stalls of co-admitted requests and preemption gaps.
- **aggregate tokens/sec**: total generated tokens / makespan (first
  arrival to last completion).

The baseline (``run_batch_baseline``) replays the SAME trace through
``infer/generate.py``'s batch-at-a-time generator: requests batch in
arrival order, the batch pads every prompt to its longest and decodes
``max(output budgets)`` steps, and nothing streams out early — so a
request's TTFT is when its whole batch returns. That is the measured
definition, not a strawman: it is exactly what serving with the
training-style generator would do. Both emit ``kind:"serve_summary"``
records through the ``obs`` sinks; ``benchmarks/regress.py`` gates the
p99/tokens-per-sec envelope in CI (docs/serving.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.serve.engine import (
    Request,
    ServingEngine,
)


@dataclass
class Workload:
    """A fully materialized open-loop trace (seeded, replayable)."""

    arrivals: np.ndarray  # [N] seconds from trace start, sorted
    prompts: list[np.ndarray]  # [N] int32 token vectors
    max_new_tokens: np.ndarray  # [N] int32

    def __len__(self) -> int:
        return len(self.prompts)


def make_poisson_workload(
    *,
    num_requests: int,
    rate_rps: float,
    prompt_len: tuple[int, int],
    output_len: tuple[int, int],
    vocab_size: int,
    seed: int = 0,
) -> Workload:
    """Poisson arrivals at ``rate_rps`` with uniform prompt/output
    lengths in the given inclusive ranges. Token ids avoid 0 (the
    conventional pad id)."""
    if num_requests < 1:
        raise ValueError(f"num_requests must be >= 1, got {num_requests}")
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_rps, size=num_requests)
    gaps[0] = 0.0  # first request arrives at t=0 — makespan starts there
    arrivals = np.cumsum(gaps)
    plens = rng.integers(prompt_len[0], prompt_len[1] + 1, num_requests)
    olens = rng.integers(output_len[0], output_len[1] + 1, num_requests)
    prompts = [
        rng.integers(1, vocab_size, size=int(n)).astype(np.int32)
        for n in plens
    ]
    return Workload(
        arrivals=arrivals,
        prompts=prompts,
        max_new_tokens=olens.astype(np.int32),
    )


def _percentile(values: list[float], q: float) -> float:
    return float(np.percentile(np.asarray(values), q)) if values else 0.0


def _summarize(
    label: str,
    reqs: list[Request],
    makespan: float,
    extra: dict[str, Any],
) -> dict[str, Any]:
    # Terminal-status accounting (serve/guard.py): every request that
    # leaves the system lands in exactly one bucket. Latency percentiles
    # are computed over requests that actually DELIVERED (completed /
    # recovered) — a rejected request has no latency, and a timed-out
    # one's truncated stream would flatter the tail.
    statuses = {"completed": 0, "rejected": 0, "timed_out": 0, "recovered": 0}
    for r in reqs:
        t = r.terminal_status
        if t in statuses:
            statuses[t] += 1
    delivered = [
        r
        for r in reqs
        if r.terminal_status in ("completed", "recovered")
        and r.first_token_time is not None
    ]
    ttfts = [
        (r.first_token_time - r.arrival_time) * 1e3 for r in delivered
    ]
    per_tok = [
        (r.done_time - r.first_token_time) * 1e3 / max(1, r.output_tokens - 1)
        for r in delivered
    ]
    # Inter-token latency: gaps between consecutive SURFACED tokens of
    # one request (streaming delivery — engine._surface). Measured, not
    # derived from the decode mean: the tail includes prefill stalls of
    # co-resident admissions and preemption gaps, which is what a
    # streaming client actually experiences. The batch baseline streams
    # nothing (token_times stays empty), so its ITL reports 0 — TTFT is
    # its honest latency metric.
    itls: list[float] = []
    for r in delivered:
        if len(r.token_times) > 1:
            diffs = np.diff(np.asarray(r.token_times))
            # A recovered request's token_times mix the dead process's
            # clock epoch with the resumed engine's: the diff across
            # each resume boundary "measures" the kill gap (or worse, a
            # negative monotonic-clock delta), not an inter-token
            # latency. Exclude exactly those gaps; every real gap —
            # including preemption stalls — still counts.
            skip = {
                b - 1
                for b in getattr(r, "resume_boundaries", ())
                if 1 <= b <= len(diffs)
            }
            itls.extend(
                float(d) * 1e3
                for i, d in enumerate(diffs)
                if i not in skip
            )
    total_tokens = sum(r.output_tokens for r in reqs)
    return {
        "kind": "serve_summary",
        "time": time.time(),
        "engine": label,
        "requests": len(reqs),
        "total_output_tokens": int(total_tokens),
        "makespan_s": round(makespan, 4),
        "ttft_p50_ms": round(_percentile(ttfts, 50), 3),
        "ttft_p99_ms": round(_percentile(ttfts, 99), 3),
        "decode_ms_per_token_p50": round(_percentile(per_tok, 50), 4),
        "itl_p50_ms": round(_percentile(itls, 50), 4),
        "itl_p99_ms": round(_percentile(itls, 99), 4),
        "tokens_per_sec": round(total_tokens / makespan, 2)
        if makespan > 0
        else 0.0,
        **statuses,
        **extra,
    }


def _emit_summary(sink: Any, record: dict[str, Any]) -> None:
    """Emit a serve_summary plus its bench-shaped twins (metric + value)
    so regress.py gates the serving envelope with its standard
    arithmetic — including the absolute budgets
    benchmarks/serve_smoke_budget.json arms. Shared by ``run_poisson``
    and ``serve/guard.py::run_serve_with_recovery``."""
    if sink is None:
        return
    sink.emit(record)
    for metric, value, unit in (
        ("serve_tokens_per_sec", record["tokens_per_sec"], "tokens/sec"),
        ("serve_ttft_p99_ms", record["ttft_p99_ms"], "ms"),
        ("serve_itl_p99_ms", record["itl_p99_ms"], "ms"),
        # chaos visibility: requests replayed from a ServeSnapshot
        # after a kill/resume (docs/reliability.md) — 0 on clean runs
        (
            "serve_recovered",
            record.get("recovered_requests", 0),
            "requests",
        ),
        # guard visibility (docs/reliability.md "Serving under failure
        # and overload"): terminal sheds and deadline expiries — 0 on
        # unguarded or under-capacity runs.
        ("serve_rejected", record.get("rejected", 0), "requests"),
        ("serve_timed_out", record.get("timed_out", 0), "requests"),
    ):
        sink.emit({
            "kind": "bench",
            "time": time.time(),
            "metric": metric,
            "value": value,
            "unit": unit,
        })


def run_poisson(
    engine: ServingEngine,
    workload: Workload,
    *,
    sink: Any = None,
    warmup: bool = True,
    watchdog: Any = None,
) -> dict[str, Any]:
    """Replay ``workload`` open-loop against the engine on the wall
    clock and return (and emit) the ``serve_summary`` record.

    ``warmup=True`` first runs one throwaway request per prefill bucket
    plus a decode step, so compile time does not pollute the measured
    TTFTs (and so the post-warmup 0-retrace contract covers the whole
    measured run). A ``StepWatchdog`` passed as ``watchdog`` arms
    around every measured engine step — wire its ``flight_recorder`` to
    ``engine.make_flight_recorder()`` so a wedged step dumps the serve
    event ring (docs/observability.md)."""
    clock = engine.clock
    if warmup:
        buckets = sorted({engine._bucket_for(len(p)) for p in workload.prompts})
        # no warmup records, no warmup spans, no warmup sheds (the
        # guard's admission counters must only see measured traffic)
        saved_sink, engine.sink = engine.sink, None
        saved_tracer, engine.tracer = engine.tracer, None
        saved_guard, engine.guard = engine.guard, None
        try:
            for b in buckets:
                plen = min(b, engine.max_seq_len - 1)
                engine.submit(
                    Request(
                        prompt=np.ones((plen,), np.int32), max_new_tokens=2
                    )
                )
            engine.run()
        finally:
            engine.sink = saved_sink
            engine.tracer = saved_tracer
            engine.guard = saved_guard
        # warmup requests must not count against the measurement
        engine._completed.clear()
        engine._preemptions = 0
        engine._timed_out = 0
        engine._shed = 0
        engine._step_count = 0
        engine._active_slot_steps = 0
        engine._trash_rows = 0
        engine._decode_walls.clear()
        engine._event_ring.clear()
        engine.pool.high_water = engine.pool.allocated_pages
        engine.pool.total_allocs = 0
        engine.pool.total_frees = 0
        if engine.tracer is not None:
            engine.tracer.reset(clock())

    t0 = clock()
    n = len(workload)
    i = 0
    submitted: list[Request] = []
    while i < n or engine.busy:
        now = clock() - t0
        while i < n and workload.arrivals[i] <= now:
            submitted.append(engine.submit(
                Request(
                    prompt=workload.prompts[i],
                    max_new_tokens=int(workload.max_new_tokens[i]),
                    arrival_time=t0 + float(workload.arrivals[i]),
                )
            ))
            i += 1
        if engine.busy:
            if watchdog is not None:
                with watchdog.watch():
                    engine.step()
            else:
                engine.step()
        elif i < n:
            # idle until the next arrival (open loop — do not pull it in
            # early; the arrival process IS the experiment)
            time.sleep(
                min(0.002, max(0.0, float(workload.arrivals[i]) - now))
            )
    engine.finalize_trace()  # flush the final partial serve_window
    reqs = engine._completed[:]
    # Terminal accounting (serve/guard.py): every submitted request must
    # resolve to exactly one terminal status — a drained engine with an
    # unresolved (or doubly-resolved) request is a scheduler bug, not a
    # metrics footnote.
    unresolved = [r.req_id for r in submitted if r.terminal_status is None]
    assert not unresolved, f"requests ended unresolved: {unresolved}"
    ids = [r.req_id for r in reqs]
    assert len(ids) == len(set(ids)), (
        f"requests resolved more than once: "
        f"{sorted({x for x in ids if ids.count(x) > 1})}"
    )
    makespan = max(r.done_time for r in reqs) - t0 if reqs else 0.0
    record = _summarize(
        "continuous",
        reqs,
        makespan,
        {
            **engine.stats(),
            "num_slots": engine.cfg.num_slots,
            "page_size": engine.cfg.page_size,
            "num_pages": engine.cfg.num_pages,
            "kv_pool_tokens": engine.cfg.num_pages * engine.cfg.page_size,
        },
    )
    _emit_summary(sink, record)
    return record


def run_batch_baseline(
    model: Any,
    params: Any,
    workload: Workload,
    *,
    batch_size: int,
    temperature: float = 0.0,
    eos_id: int | None = None,
    sink: Any = None,
    warmup: bool = True,
) -> dict[str, Any]:
    """Replay the workload through batch-at-a-time ``make_generator``:
    requests group into arrival-order batches of ``batch_size``, a batch
    launches once its last member has arrived, every prompt right-pads
    to the batch's longest, and the loop runs the batch's LONGEST output
    budget. Tokens past a request's own budget are discarded (they were
    still computed — that is the waste being measured). TTFT for every
    request in a batch is the batch's return time.

    The generator's dense KV cache holds ``batch_size * max_seq_len``
    token rows; compare ``kv_cache_tokens`` in the summary against the
    engine's ``kv_pool_tokens`` for the equal-HBM framing."""
    from cs744_pytorch_distributed_tutorial_tpu.infer import make_generator

    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    budget_max = int(np.max(workload.max_new_tokens))
    gen = make_generator(
        model,
        max_new_tokens=budget_max,
        temperature=temperature,
        eos_id=eos_id,
    )
    plen_max = max(len(p) for p in workload.prompts)
    if warmup:
        gen(
            params,
            np.ones((batch_size, plen_max), np.int32),
            jax.random.key(0),
        )[0].block_until_ready()

    clock = time.monotonic
    t0 = clock()
    reqs: list[Request] = []
    n = len(workload)
    for start in range(0, n, batch_size):
        idx = list(range(start, min(start + batch_size, n)))
        batch_arrival = t0 + float(workload.arrivals[idx[-1]])
        now = clock()
        if now < batch_arrival:
            time.sleep(batch_arrival - now)
        plen = max(len(workload.prompts[j]) for j in idx)
        prompt = np.zeros((batch_size, plen), np.int32)
        for row, j in enumerate(idx):
            p = workload.prompts[j]
            # right-padded: shorter prompts condition on pad tokens past
            # their true length — one more batch-at-a-time artifact the
            # per-request engine simply does not have
            prompt[row, : len(p)] = p
        launch = clock()
        out = np.asarray(gen(params, prompt, jax.random.key(start)))
        done = clock()
        for row, j in enumerate(idx):
            budget = int(workload.max_new_tokens[j])
            toks = out[row, :budget].tolist()
            if eos_id is not None and eos_id in toks:
                toks = toks[: toks.index(eos_id) + 1]
            r = Request(
                prompt=workload.prompts[j],
                max_new_tokens=budget,
                req_id=j,
                arrival_time=t0 + float(workload.arrivals[j]),
            )
            r.orig_prompt_len = len(workload.prompts[j])
            r.orig_max_new_tokens = budget
            r.generated = toks
            r.submit_time = launch
            # batch-at-a-time streams nothing: the first token a client
            # sees arrives when the whole batch returns
            r.first_token_time = done
            r.done_time = done
            reqs.append(r)
    makespan = max(r.done_time for r in reqs) - t0 if reqs else 0.0
    record = _summarize(
        "batch",
        reqs,
        makespan,
        {
            "batch_size": batch_size,
            "kv_cache_tokens": batch_size * model.max_seq_len,
        },
    )
    if sink is not None:
        sink.emit(record)
    return record
