"""Host-side page accounting for the paged KV pool.

The device side is dumb on purpose: per-layer pools of
``[num_pages, page_size, Hkv, D]`` plus a ``[B, P]`` page table, all
fixed-shape so the decode step never retraces. Everything that *varies*
— which pages belong to which request, what is free — lives here as
plain Python, mutated between steps.

Page 0 is reserved as the TRASH page: inactive slots point their whole
table row at it, so the (unavoidable — fixed-shape step) writes from
dead slots land somewhere no live slot ever gathers. It is never
allocated.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PagePool:
    """LIFO free-list allocator over ``num_pages`` KV pages.

    LIFO keeps the working set of page indices small and recently
    touched (cache-friendly scatter/gather on device), and makes tests
    deterministic. ``high_water`` tracks the max simultaneously
    allocated pages — the number the HBM budget must actually cover.
    """

    num_pages: int
    page_size: int
    _free: list[int] = field(default_factory=list)
    _live: set[int] = field(default_factory=set)
    _allocated: int = 0
    high_water: int = 0
    # Cumulative churn counters (graftserve pool telemetry,
    # obs/serve_trace.py): pages handed out / returned over the pool's
    # lifetime — their per-window delta is the allocation pressure the
    # serve_window records report as ``page_churn``.
    total_allocs: int = 0
    total_frees: int = 0

    def __post_init__(self) -> None:
        if self.num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved as trash), "
                f"got {self.num_pages}"
            )
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        # Page 0 is the trash page — excluded. Reversed so that pages
        # allocate in ascending order (pop from the end).
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._live = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return self._allocated

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` KV rows (ceil division)."""
        return -(-tokens // self.page_size)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: want {n}, have {len(self._free)} "
                f"free of {self.num_pages - 1} allocatable"
            )
        out = [self._free.pop() for _ in range(n)]
        self._live.update(out)
        self._allocated += n
        self.total_allocs += n
        self.high_water = max(self.high_water, self._allocated)
        return out

    def free(self, pages: list[int]) -> None:
        # Validate against the LIVE set, not just the free list: the old
        # ``p in self._free`` check let a page duplicated WITHIN one call
        # (``free([3, 3])``) slip through silently — the free list grew a
        # duplicate entry and the same page could later be handed to two
        # slots. ``seen`` catches the intra-call duplicate, ``_live``
        # catches everything else (already-free or never-allocated).
        seen: set[int] = set()
        for p in pages:
            if p == 0:
                raise ValueError("page 0 is the reserved trash page")
            if not (0 < p < self.num_pages):
                raise ValueError(f"page index {p} out of range")
            if p in seen or p not in self._live:
                raise ValueError(f"double free of page {p}")
            seen.add(p)
        # Freed pages go back on TOP of the stack — reused first.
        self._free.extend(reversed(pages))
        self._live.difference_update(seen)
        self._allocated -= len(pages)
        self.total_frees += len(pages)

    def check_invariants(self) -> bool:
        """Debug audit of the page accounting; raises AssertionError on
        any violation, returns True when clean (so tests can assert it).

        The engine calls this under ``__debug__`` at every retire /
        preempt / deadline-expiry free — the paths where a bookkeeping
        bug would silently leak (or double-lease) pages:

        - free-list ∪ live pages == every allocatable page (none leaked),
        - free-list ∩ live pages == ∅ (no page both free and leased),
        - the trash page (0) is never allocated and never on the free
          list,
        - the counters agree with the sets.
        """
        free = set(self._free)
        allocatable = set(range(1, self.num_pages))
        assert len(free) == len(self._free), (
            f"free list holds duplicate pages: {sorted(self._free)}"
        )
        assert 0 not in free and 0 not in self._live, (
            "trash page 0 was allocated or freed"
        )
        assert not (free & self._live), (
            f"pages both free and live: {sorted(free & self._live)}"
        )
        assert free | self._live == allocatable, (
            f"pages leaked: {sorted(allocatable - free - self._live)}"
        )
        assert self._allocated == len(self._live), (
            f"allocated counter {self._allocated} != "
            f"{len(self._live)} live pages"
        )
        return True
