"""Request-level serving: continuous batching over a paged KV pool.

``engine.ServingEngine`` runs the in-flight batching loop (fixed-shape
jitted decode step over B slots; slots retire and refill independently;
KV lives in per-layer page pools so memory scales with live tokens).
``pool.PagePool`` owns page accounting, ``loadgen`` replays Poisson
arrivals and reports TTFT / per-token latency / tokens-per-sec through
the ``obs`` sinks. See docs/serving.md.
"""

from cs744_pytorch_distributed_tutorial_tpu.serve.engine import (  # noqa: F401
    Request,
    ServeConfig,
    ServeSnapshot,
    ServingEngine,
)
from cs744_pytorch_distributed_tutorial_tpu.serve.guard import (  # noqa: F401
    GuardConfig,
    ServeGuard,
    run_serve_with_recovery,
)
from cs744_pytorch_distributed_tutorial_tpu.serve.loadgen import (  # noqa: F401
    Workload,
    make_poisson_workload,
    run_batch_baseline,
    run_poisson,
)
from cs744_pytorch_distributed_tutorial_tpu.serve.pool import (  # noqa: F401
    PagePool,
)
