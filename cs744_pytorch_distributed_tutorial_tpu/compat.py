"""Compatibility shims for older jax releases.

The codebase targets the current shard_map API — ``jax.shard_map`` with
``check_vma`` and the varying-manual-axes collectives (``lax.pcast``,
``jax.typeof(...).vma``). On older jax (<= 0.4.x) those names live
elsewhere or do not exist:

- ``jax.shard_map``          -> ``jax.experimental.shard_map.shard_map``
- ``check_vma=...``          -> ``check_rep=...`` (see below)
- ``lax.pcast(x, ax, to=..)``-> ``shard_map.pbroadcast`` (old spelling
  of replicated->varying; only ``to="varying"`` is ever used here)
- ``jax.typeof``             -> ``jax.core.get_aval`` (no ``.vma`` attr;
  every call site already guards with ``getattr(..., "vma", ...)``)

``check_vma`` maps to ``check_rep`` by value. The mapping must NOT be a
blanket ``check_rep=False``: without the checker the old transposition
rules reduce to pmap's (``psum`` transposes to ``psum``), which makes
differentiating a pmean'd loss w.r.t. replicated params return
unaveraged/axis-size-inflated gradients — the sync-parity suite catches
this as a ~N_devices blowup on 'auto'. ``check_rep=True`` type-checks
the manual strategies because ``pcast(..., to="varying")`` lowers to
``pbroadcast``: params are cast *before* differentiation, so grads come
out device-varying/local, and the strategy's explicit psum/pmean both
satisfies the checker and produces replicated outputs. What the old
checker can NOT do is follow AD-*inserted* collectives (the 'auto'
path's contract), so ``LEGACY_SHARD_MAP`` is exported for the train
engine to reroute 'auto'/'none' through the explicit-pmean path —
numerically identical to what new-jax vma-aware AD inserts. Call sites
that genuinely cannot be checked (unreduced manual collectives,
compressed sync) already pass ``check_vma=False`` and flow through to
``check_rep=False`` unchanged.

Imported for its side effects from the package ``__init__``; a no-op on
current jax. Set ``CS744_COMPAT=0`` to skip installation (exposes the
raw API surface, e.g. to reproduce stock-jax behavior in CI matrices).
"""

from __future__ import annotations

import functools
import inspect
import os

import jax
from jax import lax

__all__ = ["LEGACY_SHARD_MAP", "install"]

#: True when this jax predates ``jax.shard_map``/vma tracking and the
#: shims below are (about to be) installed. Evaluated BEFORE install()
#: so it reflects the real jax, not the shimmed surface.
LEGACY_SHARD_MAP: bool = not hasattr(jax, "shard_map")


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def shard_map(f, /, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _legacy_shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=bool(check_vma),
        )

    jax.shard_map = shard_map


def _install_pcast() -> None:
    if hasattr(lax, "pcast"):
        return

    from jax.experimental.shard_map import pbroadcast as _pbroadcast

    def pcast(x, axis_name, *, to):
        # Old shard_map spells replicated->varying as pbroadcast; under
        # check_rep=True it marks x device-varying so downstream explicit
        # psum/pmean type-check, and its evaluation is the identity.
        if to != "varying":
            raise NotImplementedError(
                f"compat pcast only supports to='varying', got {to!r}"
            )
        return _pbroadcast(x, axis_name)

    lax.pcast = pcast


def _install_typeof() -> None:
    if hasattr(jax, "typeof"):
        return

    def typeof(x):
        return jax.core.get_aval(x)

    jax.typeof = typeof


def install() -> None:
    if os.environ.get("CS744_COMPAT", "1") == "0":
        return
    _install_shard_map()
    _install_pcast()
    _install_typeof()


install()

# Quiet an inspect oddity: functools.wraps on a function whose original
# has positional-only markers can confuse signature() consumers; make
# sure the wrapper is introspectable (best-effort, never fatal).
try:
    inspect.signature(jax.shard_map)
except (TypeError, ValueError):
    pass
