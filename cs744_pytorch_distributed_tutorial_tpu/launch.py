"""graftelastic launcher: supervised elastic multi-process runs.

The reference's launch story is "start 4 processes by hand on 4
CloudLab nodes and hope none dies" (``init_process`` pins
``MASTER_ADDR``/``MASTER_PORT``; SURVEY §5.3). This CLI is the
replacement: a supervisor (``parallel/multihost.py::launch_local``)
that spawns N workers, watches heartbeats and exit codes, and re-execs
the survivors into generation g+1 — with a deterministically
re-elected coordinator — when a rank dies.

Supervise any worker command (it learns its coordinates from the
``GRAFT_ELASTIC_*`` environment, or its own ``--coordinator`` flags)::

    python -m cs744_pytorch_distributed_tutorial_tpu.launch \\
        --nprocs 4 --store /tmp/elastic -- \\
        python -m cs744_pytorch_distributed_tutorial_tpu.cli --plan 2b

Or run the built-in demo worker — a tiny-CNN data-parallel loop with
per-step durable checkpoints and a scheduled chaos kill — which is the
e2e harness for kill/re-election (tests/test_multihost.py, the
multihost-smoke CI job)::

    python -m cs744_pytorch_distributed_tutorial_tpu.launch \\
        --nprocs 4 --store /tmp/elastic --steps 8 --kill 4:2

``--kill STEP:RANK`` SIGKILLs the given GLOBAL rank at the given
cumulative step (rank 0 = the coordinator — killing it exercises
re-election). ``--slow RANK:MS`` stalls the given GLOBAL rank for MS
milliseconds before every step — a seeded straggler whose late
collective arrivals graftfleet's cross-rank skew attribution must pin.
The demo worker checkpoints every step, so the resumed generation's
loss trajectory is comparable (rtol 1e-6) against an uninterrupted run
at the shrunk world size — the acceptance bar for the elastic path.
Per-rank stdout lands in ``<store>/logs/``; the supervisor+worker
event timeline in ``<store>/events.jsonl``; each rank stamps its
step/collective boundaries into ``<store>/fleet/``, and the supervisor
merges everything into ``<store>/fleet_trace.json`` (Perfetto) +
``fleet_report.json`` at exit (``obs/fleet.py``; re-render or audit
any time with ``python -m …obs fleet-report <store> --check``).
"""

from __future__ import annotations

import argparse
import os
import sys

from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
    CollectiveWatchdog,
    RendezvousStore,
    attach,
    env_context,
    launch_local,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger


class _StoreTelemetry:
    """Adapter: ``emit_event``-shaped telemetry that appends to the
    rendezvous store's shared events.jsonl — chaos injections from any
    rank land on the same timeline as the supervisor's transitions, and
    the append is durable before a self-SIGKILL returns."""

    def __init__(self, store: RendezvousStore):
        self.store = store

    def emit_event(self, event: str, **fields) -> None:
        self.store.append_event(event, **fields)


def _parse_kill(spec: str) -> tuple[int, int]:
    try:
        step_s, rank_s = spec.split(":")
        return int(step_s), int(rank_s)
    except ValueError as e:
        raise SystemExit(f"--kill expects STEP:RANK, got {spec!r}") from e


def _parse_slow(spec: str) -> tuple[int, float]:
    try:
        rank_s, ms_s = spec.split(":")
        return int(rank_s), float(ms_s)
    except ValueError as e:
        raise SystemExit(f"--slow expects RANK:MS, got {spec!r}") from e


def _worker_train(args: argparse.Namespace) -> int:
    """The built-in demo worker: one elastic data-parallel tiny-CNN loop.

    Deliberately layout-invariant so the e2e's rtol 1e-6 bar is about
    ELASTICITY, not luck: ``sync_bn=True`` (global-batch BN statistics —
    identical math at any world size), ``augment=False``, one fixed
    synthetic global batch divisible by every world size it will see,
    and the trainer's own step-folded PRNG (resume at step K draws step
    K's key regardless of generation). World size is then a layout
    choice, and the resumed trajectory must match an uninterrupted run
    at the shrunk world bit-for-bit-ish.
    """
    ctx = env_context()
    if ctx is None:
        raise SystemExit(
            "--worker-train needs the GRAFT_ELASTIC_* environment "
            "(it is spawned by the supervisor, not run by hand)"
        )
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # The deployment's sitecustomize force-selects the TPU platform
        # via jax.config, which outranks the env var the supervisor set
        # — override through the same channel.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    store = RendezvousStore(ctx.store_dir)
    hb = attach(ctx)  # rendezvous + heartbeats + identity labels
    log = get_logger()

    from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig
    from cs744_pytorch_distributed_tutorial_tpu.data import synthetic_cifar10
    from cs744_pytorch_distributed_tutorial_tpu.obs.fleet import (
        FleetStamper,
        stamp_pair,
    )
    from cs744_pytorch_distributed_tutorial_tpu.parallel import make_mesh
    from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
        shard_global_batch,
    )
    from cs744_pytorch_distributed_tutorial_tpu.train import Trainer
    from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
        ChaosMonkey,
        FaultSchedule,
    )
    from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
        Checkpointer,
    )

    n_dev = jax.device_count()
    mesh = make_mesh({"data": n_dev})
    cfg = TrainConfig(
        model="tiny_cnn",
        sync="allreduce",
        sync_bn=True,
        augment=False,
        num_devices=n_dev,
        global_batch_size=args.global_batch,
        synthetic_data=True,
        synthetic_train_size=args.global_batch,
        synthetic_test_size=8,
        seed=0,
        # Modest LR: keeps the demo's losses O(1) for its whole run, so
        # the e2e's rtol-1e-6 cross-world parity bar measures ELASTIC
        # correctness, not float noise amplified by a near-zero loss
        # (reduction order differs across world sizes by ~1e-7 rel).
        learning_rate=args.lr,
    )
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init()

    ckpt = Checkpointer(os.path.join(store.root, "ckpt"))
    start = 0
    if ckpt.latest_step() is not None:
        # After a re-exec only the disk tier survives (the in-memory
        # snapshot died with the old process) — restore-tier
        # arbitration is trivial here; docs/reliability.md has the
        # general table.
        state = trainer.place_state(ckpt.restore_latest(state))
        start = int(jax.device_get(state.step))
        store.append_event(
            "recovery_resume",
            step=start,
            tier="disk",
            world_size=ctx.num_processes,
        )
        log.info(
            "graftelastic demo: resumed from disk at step %d "
            "(generation %d, world %d)",
            start,
            ctx.generation,
            ctx.num_processes,
        )

    # Arrival stamping (obs/fleet.py): wrap train_step so sync_enter is
    # taken immediately before the step dispatches. Cross-process CPU
    # collectives block at DISPATCH (the psum rendezvous is inside the
    # train_step call, not behind the fetch), so this pre-dispatch
    # instant is the rank's true arrival at the collective — any chaos
    # stall installed OUTSIDE this wrapper delays it, and early ranks
    # spend the gap blocked inside the step waiting. The monkeys below
    # must wrap this, so install it first.
    arrival: dict[str, tuple[float, float]] = {}
    _unstamped_step = trainer.train_step

    def _stamped_step(*step_args, **step_kwargs):
        arrival["sync_enter"] = stamp_pair()
        return _unstamped_step(*step_args, **step_kwargs)

    trainer.train_step = _stamped_step

    if args.kill:
        kill_step, kill_rank = _parse_kill(args.kill)
        schedule = FaultSchedule(
            {kill_step: {"kind": "process_kill", "rank": kill_rank}}
        )
        # first_call=start keeps the schedule keyed by ABSOLUTE step
        # across generations; targeting the global rank makes a
        # re-parsed spec inert once that rank is dead.
        ChaosMonkey(
            schedule,
            telemetry=_StoreTelemetry(store),
            rank=ctx.global_rank,
            first_call=start,
        ).install(trainer)
    if args.slow:
        slow_rank, slow_ms = _parse_slow(args.slow)
        # A stall at EVERY step of the run: the schedule targets the
        # global rank, so survivors re-parsing it keep the same
        # straggler across generations. Installed after --kill's monkey
        # (wrapping it), so the stall precedes the kill check.
        ChaosMonkey(
            FaultSchedule(
                {
                    s: {
                        "kind": "slow_step",
                        "rank": slow_rank,
                        "stall_s": slow_ms / 1e3,
                    }
                    for s in range(args.steps)
                }
            ),
            telemetry=_StoreTelemetry(store),
            rank=ctx.global_rank,
            first_call=start,
        ).install(trainer)

    watchdog = CollectiveWatchdog(
        store, ctx, deadline_s=args.collective_deadline_s
    )
    # Per-rank fleet stamps (obs/fleet.py): step boundaries plus the
    # sync window around the blocking fetch. Dispatch is async, so
    # sync_enter is this rank's ARRIVAL at the collective — the stamp
    # graftfleet aligns across ranks to name the straggler. The demo
    # fetches every step anyway, so the stamps add no host syncs.
    stamper = FleetStamper(
        store.root, ctx.generation, ctx.global_rank, ctx.process_id
    )
    ds = synthetic_cifar10(args.global_batch, 8, seed=0)
    x, y = shard_global_batch(mesh, ds.train_images, ds.train_labels)
    key = jax.random.key(cfg.seed)
    for step in range(start, args.steps):
        watchdog.check()
        step_enter = stamp_pair()
        with watchdog.watch():
            # Step + fetch + durable save are ONE watched section: all
            # three can block on a dead peer (the psum, the result
            # fetch behind it, Orbax's cross-process commit barrier).
            state, metrics = trainer.train_step(state, x, y, key)
            loss = float(jax.device_get(metrics["loss"]))
            sync_exit = stamp_pair()
            ckpt.save(state, force=True, wait=True)
        step_exit = stamp_pair()
        stamper.stamp_step(
            step,
            step_enter=step_enter,
            sync_enter=arrival.get("sync_enter", step_enter),
            sync_exit=sync_exit,
            step_exit=step_exit,
        )
        hb.step = step
        print(
            f"[graftelastic] gen={ctx.generation} grank={ctx.global_rank} "
            f"step={step} loss={loss:.8f}",
            flush=True,
        )
    watchdog.close()
    stamper.close()
    ckpt.close()
    hb.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cs744_pytorch_distributed_tutorial_tpu.launch",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--nprocs", type=int, default=4,
                   help="workers in generation 0 (default 4)")
    p.add_argument("--store", required=False, default=None,
                   help="rendezvous store directory (shared filesystem); "
                        "required in supervisor mode")
    p.add_argument("--max-generations", type=int, default=4,
                   help="give up after this many re-exec generations")
    p.add_argument("--heartbeat-deadline-s", type=float, default=15.0,
                   help="a running rank whose heartbeat is older than "
                        "this is declared dead")
    p.add_argument("--startup-grace-s", type=float, default=180.0,
                   help="allowance for a rank's first heartbeat "
                        "(imports + rendezvous)")
    p.add_argument("--exit-grace-s", type=float, default=30.0,
                   help="teardown: how long survivors get to exit on "
                        "their own (via their collective watchdog) "
                        "before SIGTERM/SIGKILL escalation")
    p.add_argument("--platform", choices=("cpu", "inherit"), default="cpu",
                   help="'cpu' pins workers to one CPU device each "
                        "(CI/laptop); 'inherit' leaves the environment "
                        "alone (pod runs)")
    # Demo-worker knobs (also forwarded by the supervisor when no
    # explicit worker command is given after `--`).
    p.add_argument("--steps", type=int, default=8,
                   help="demo worker: total train steps")
    p.add_argument("--global-batch", type=int, default=12,
                   help="demo worker: fixed global batch — keep it "
                        "divisible by every world size the run may "
                        "shrink to")
    p.add_argument("--lr", type=float, default=0.002,
                   help="demo worker: SGD learning rate")
    p.add_argument("--kill", default=None, metavar="STEP:RANK",
                   help="demo worker: SIGKILL global rank RANK at "
                        "cumulative step STEP (0 = coordinator)")
    p.add_argument("--slow", default=None, metavar="RANK:MS",
                   help="demo worker: stall global rank RANK for MS "
                        "milliseconds before every step (seeded "
                        "straggler for fleet skew attribution)")
    p.add_argument("--collective-deadline-s", type=float, default=8.0,
                   help="demo worker: watchdog deadline for a step "
                        "blocked on a dead peer")
    p.add_argument("--worker-train", action="store_true",
                   help=argparse.SUPPRESS)  # internal: demo worker mode
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command after `--` (default: the "
                        "built-in demo worker)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.worker_train:
        return _worker_train(args)
    if not args.store:
        raise SystemExit("supervisor mode requires --store DIR")

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        cmd = [
            sys.executable,
            "-m",
            "cs744_pytorch_distributed_tutorial_tpu.launch",
            "--worker-train",
            "--steps", str(args.steps),
            "--global-batch", str(args.global_batch),
            "--lr", str(args.lr),
            "--collective-deadline-s", str(args.collective_deadline_s),
        ]
        if args.kill:
            cmd += ["--kill", args.kill]
        if args.slow:
            cmd += ["--slow", args.slow]

    env = None
    if args.platform == "cpu":
        # One CPU device per process: clear any virtual-device XLA
        # flags and the deployment's TPU-pool autodetection.
        env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",
            "PALLAS_AXON_POOL_IPS": "",
        }

    run = launch_local(
        args.nprocs,
        cmd,
        store_dir=args.store,
        env=env,
        max_generations=args.max_generations,
        heartbeat_deadline_s=args.heartbeat_deadline_s,
        startup_grace_s=args.startup_grace_s,
        exit_grace_s=args.exit_grace_s,
    )
    log = get_logger()
    for world in run.generations:
        log.info(
            "generation %d: world %s exit codes %s dead %s",
            world["generation"],
            world["ranks"],
            world.get("exit_codes", {}),
            world.get("dead", []),
        )
    log.info(
        "graftelastic: %s after %d generation(s); events at %s",
        "completed" if run.success else "FAILED",
        len(run.generations),
        run.store.events_path,
    )
    # Merge everything the run left behind into the fleet artifacts
    # (Perfetto timeline + skew/incident report). Best-effort: a merge
    # failure must never change the run's exit code.
    try:
        from cs744_pytorch_distributed_tutorial_tpu.obs.fleet import (
            write_fleet_artifacts,
        )

        artifacts = write_fleet_artifacts(run.store.root)
        log.info(
            "graftfleet: merged timeline at %s (%d audit problem(s))",
            artifacts["trace"],
            len(artifacts["problems"]),
        )
    except Exception:
        log.warning("graftfleet: artifact merge failed", exc_info=True)
    return 0 if run.success else 1


if __name__ == "__main__":
    raise SystemExit(main())
