"""TPU-native distributed-training framework.

A brand-new JAX/XLA framework with the capabilities of the CS744 PyTorch
distributed tutorial (reference: kkyyhh96/CS744_PyTorch_Distributed_Tutorial).
The reference is four progressively more automated implementations of
data-parallel SGD training of VGG-11 on CIFAR-10 over 4 ranks
(gather/scatter, p2p star, allreduce, DDP). This framework re-expresses
that as ONE single-program SPMD engine with pluggable gradient-sync
strategies running over a `jax.sharding.Mesh`:

- the reference's master/slave dual source trees (rank asymmetry as two
  parallel file trees) become single-program `shard_map` code where rank
  asymmetry, where needed, is `lax.axis_index` arithmetic;
- Gloo collectives over TCP become XLA collectives over ICI/DCN
  (`psum`, `all_gather`, `ppermute`);
- `torch.distributed.init_process_group` becomes
  `jax.distributed.initialize`;
- tape autograd + DDP's C++ reducer become `jax.grad` inside one jitted
  step, with XLA's latency-hiding scheduler providing the compute/comm
  overlap DDP's bucketing provides.
"""

__version__ = "0.1.0"

# Side-effect import: backfills jax.shard_map / lax.pcast / jax.typeof
# on older jax releases so one source tree runs across API versions.
from cs744_pytorch_distributed_tutorial_tpu import compat as _compat  # noqa: F401
from cs744_pytorch_distributed_tutorial_tpu.config import TrainConfig

__all__ = ["TrainConfig", "__version__"]
