"""graftscope flight recorder: straggler detection + crash-time dumps.

A 30-minute run that dies, hangs, or slows down leaves nothing behind
unless someone was watching a dashboard. This module keeps a bounded
in-memory tail of per-step timing — cheap enough to run always-on —
and dumps it as structured ``kind="event"`` telemetry records when
something goes wrong:

- **StragglerMonitor**: MAD-based outlier detection over a ring of
  per-step wall times. The median/MAD pair is robust to the outliers
  it hunts (a mean/stddev detector would let one 10x step inflate its
  own threshold); the sigma floor keeps sub-millisecond CPU steps from
  flagging scheduler noise.
- **HbmHighWater**: per-device ``peak_bytes_in_use`` deltas — a step
  that suddenly allocates (retrace, fragmentation) shows up here even
  when its wall time doesn't.
- **FlightRecorder**: binds the above to a Telemetry instance and dumps
  the tail on demand. ``install()`` chains SIGTERM and
  ``sys.excepthook`` so preemptions and crashes self-report; the
  StepWatchdog (``utils/failure.py``) calls ``dump("watchdog")`` when a
  step wedges.

Everything here is host-side bookkeeping around ``time`` values already
on the host — nothing touches a traced scope (GL001/GL007-clean by
construction).
"""

from __future__ import annotations

import signal
import statistics
import sys
import threading
import time
from collections import deque
from typing import Any, Callable

__all__ = ["StragglerMonitor", "HbmHighWater", "FlightRecorder"]

# 1 MAD of a normal distribution = 1/1.4826 sigma.
_MAD_TO_SIGMA = 1.4826


class StragglerMonitor:
    """Per-step wall-time ring with MAD outlier detection.

    ``record(step, wall_s)`` judges the new step against the PRIOR
    window (so an outlier cannot vote on its own threshold), then
    appends it. Returns an outlier dict or None. Thread-compatible with
    the engines' single-threaded step loops; not locked.
    """

    def __init__(
        self,
        window: int = 512,
        mad_k: float = 5.0,
        min_samples: int = 16,
        floor_s: float = 1e-4,
        max_outliers: int = 32,
    ):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.window = window
        self.mad_k = float(mad_k)
        self.min_samples = int(min_samples)
        self.floor_s = float(floor_s)
        # (step, wall_s, t_wall, t_mono) — the record-time stamp pair
        # makes dumped tails placeable on the merged fleet timeline.
        self._ring: deque[tuple[int, float, float, float]] = deque(
            maxlen=window
        )
        self.outliers: deque[dict[str, Any]] = deque(maxlen=max_outliers)
        self.steps_recorded = 0
        self._max_s = 0.0

    def _median_mad(self) -> tuple[float, float]:
        vals = [entry[1] for entry in self._ring]
        med = statistics.median(vals)
        mad = statistics.median(abs(v - med) for v in vals)
        return med, mad

    def record(self, step: int, wall_s: float) -> dict[str, Any] | None:
        """Record one step; return an outlier record if this step is a
        straggler relative to the window BEFORE it."""
        wall_s = float(wall_s)
        out = None
        if len(self._ring) >= self.min_samples:
            med, mad = self._median_mad()
            # Floored sigma: MAD=0 (perfectly uniform window) must not
            # make every jitter an outlier, and a 5%-of-median floor
            # absorbs ordinary scheduler noise on fast steps.
            sigma = max(_MAD_TO_SIGMA * mad, 0.05 * med, self.floor_s)
            if wall_s > med + self.mad_k * sigma:
                out = {
                    "step": int(step),
                    "wall_s": wall_s,
                    "median_s": med,
                    "mad_s": mad,
                    "excess_sigma": (wall_s - med) / sigma,
                    "t_wall": time.time(),
                    "t_mono": time.monotonic(),
                }
                self.outliers.append(out)
        self._ring.append((int(step), wall_s, time.time(), time.monotonic()))
        self.steps_recorded += 1
        self._max_s = max(self._max_s, wall_s)
        return out

    def stats(self) -> dict[str, Any]:
        s: dict[str, Any] = {
            "steps_recorded": self.steps_recorded,
            "window": len(self._ring),
            "outlier_count": len(self.outliers),
            "max_s": self._max_s,
        }
        if len(self._ring) >= 2:
            med, mad = self._median_mad()
            s["median_s"] = med
            s["mad_s"] = mad
        return s

    def tail(self, n: int = 32) -> list[dict[str, Any]]:
        return [
            {
                "step": step,
                "wall_s": wall_s,
                "t_wall": t_wall,
                "t_mono": t_mono,
            }
            for step, wall_s, t_wall, t_mono in list(self._ring)[-n:]
        ]


class HbmHighWater:
    """Per-device HBM high-water tracking via ``memory_stats()``.

    ``snapshot()`` re-reads each device and returns the devices whose
    ``peak_bytes_in_use`` ROSE since the last snapshot (delta records).
    Devices without memory stats (CPU) contribute nothing.
    """

    def __init__(self, devices: Any = None):
        from .system import hbm_stats

        self._hbm_stats = hbm_stats
        if devices is None:
            import jax

            devices = jax.local_devices()
        self.devices = list(devices)
        self._peaks: dict[int, int] = {}
        self.snapshot()  # establish the baseline

    def snapshot(self) -> list[dict[str, Any]]:
        deltas = []
        for i, d in enumerate(self.devices):
            stats = self._hbm_stats(d)
            if not stats or "peak_bytes_in_use" not in stats:
                continue
            peak = int(stats["peak_bytes_in_use"])
            prev = self._peaks.get(i)
            if prev is not None and peak > prev:
                deltas.append(
                    {
                        "device": i,
                        "peak_bytes_in_use": peak,
                        "delta_bytes": peak - prev,
                        "bytes_in_use": stats.get("bytes_in_use"),
                    }
                )
            self._peaks[i] = peak
        return deltas

    def highwater(self) -> dict[str, int]:
        return {f"hbm_peak_dev{i}": p for i, p in sorted(self._peaks.items())}


class FlightRecorder:
    """Dumps the straggler/timing tail as structured telemetry events.

    One ``flight_dump`` header event (reason, straggler stats, HBM
    high-water), then one ``flight_step`` event per tail step and one
    ``flight_straggler`` event per recorded outlier — flat records so
    every sink (JSONL, stream, ring) can carry them and
    ``metrics_summary`` can count them. Dump triggers: watchdog fire
    (wired in ``utils/failure.py``), uncaught exception + SIGTERM (via
    ``install()``), or an explicit call.

    Engines can attach domain state: ``header_fn`` returns extra flat
    fields merged into the ``flight_dump`` header (e.g. the serving
    engine's pool high-water and queue depth), and each ``tails`` entry
    (name -> zero-arg fn returning flat records) dumps its last
    ``ring_tail`` records as ``flight_<name>`` events — the serving
    engine hands its serve-event ring over this way
    (``ServingEngine.make_flight_recorder``).
    """

    def __init__(
        self,
        telemetry: Any = None,
        straggler: StragglerMonitor | None = None,
        hbm: HbmHighWater | None = None,
        ring_tail: int = 32,
        emit: Callable[..., None] | None = None,
        tails: dict[str, Callable[[], list]] | None = None,
        header_fn: Callable[[], dict] | None = None,
    ):
        if telemetry is None and emit is None:
            raise ValueError("FlightRecorder needs a telemetry or an emit fn")
        self._emit = emit if emit is not None else telemetry.emit_event
        self.straggler = straggler
        self.hbm = hbm
        self._tails = dict(tails or {})
        self._header_fn = header_fn
        self.ring_tail = int(ring_tail)
        self.dumps = 0
        self._lock = threading.Lock()
        self._prev_sigterm: Any = None
        self._prev_excepthook: Any = None
        self._installed = False

    def dump(self, reason: str, **extra: Any) -> None:
        """Emit the flight tail. Never raises: this runs on the way down
        (crash, preemption, hang) and must not mask the original error."""
        with self._lock:
            self.dumps += 1
            try:
                header: dict[str, Any] = {"reason": reason, **extra}
                if self.straggler is not None:
                    for k, v in self.straggler.stats().items():
                        header[f"straggler_{k}"] = v
                if self.hbm is not None:
                    self.hbm.snapshot()
                    header.update(self.hbm.highwater())
                if self._header_fn is not None:
                    header.update(self._header_fn())
                self._emit("flight_dump", **header)
                if self.straggler is not None:
                    for rec in self.straggler.tail(self.ring_tail):
                        self._emit("flight_step", **rec)
                    for out in list(self.straggler.outliers):
                        self._emit("flight_straggler", **out)
                for name, tail_fn in self._tails.items():
                    for rec in list(tail_fn())[-self.ring_tail:]:
                        self._emit(f"flight_{name}", **rec)
            except Exception:
                pass

    # -- process-level triggers ------------------------------------------

    def install(self, sigterm: bool = True, excepthook: bool = True) -> None:
        """Chain SIGTERM + uncaught-exception dumps. Previous handlers
        still run (preemption semantics are preserved: after dumping, a
        default-action SIGTERM is re-raised so the process still dies)."""
        if self._installed:
            return
        if excepthook:
            prev_hook = sys.excepthook
            self._prev_excepthook = prev_hook

            def hook(exc_type, exc, tb):
                self.dump("exception", error=repr(exc))
                prev_hook(exc_type, exc, tb)

            sys.excepthook = hook
        if sigterm:
            try:
                prev = signal.signal(signal.SIGTERM, self._on_sigterm)
                self._prev_sigterm = prev
            except ValueError:
                # Not the main thread — signal handlers can't be set
                # here; excepthook/watchdog triggers still work.
                self._prev_sigterm = None
        self._installed = True

    def _on_sigterm(self, signum, frame):
        self.dump("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # Honor the default action: die of SIGTERM with the handler
            # out of the way so the re-raise isn't caught again.
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            signal.raise_signal(signal.SIGTERM)

    def uninstall(self) -> None:
        if not self._installed:
            return
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None
        self._installed = False
