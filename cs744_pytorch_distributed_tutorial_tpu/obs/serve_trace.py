"""graftserve: request-level tracing + windowed SLO telemetry for serve/.

The serving engine's end-of-request records say WHAT a request's TTFT
was; this module records WHY. Three layers, all host-side bookkeeping
over timestamps the engine already takes (zero device work, zero extra
syncs — the post-warmup 0-retrace contract holds with tracing on):

- **Span timeline** (:class:`ServeTracer`): every request's lifecycle as
  closed spans — ``queue`` -> ``prefill[bucket=K]`` (or ``recompute``
  after a LIFO preemption / ``resume-replay`` after a kill+resume) ->
  coalesced ``decode_run`` spans (one per contiguous residency in a
  slot, NOT one per token) -> ``retire``, with ``preempt`` instants in
  between. Exportable as Chrome/Perfetto trace-event JSON: one lane per
  decode slot, an async-span lane for queue waits, and counter tracks
  for the pool (live/free pages, active slots, queue depth).
  :func:`check_spans` is the consistency gate CI runs — no orphan,
  unclosed, or overlapping spans — and :func:`reconcile` cross-checks
  span arithmetic against the engine's recorded TTFT/stream times.
- **Windowed SLO tracker**: ``kind:"serve_window"`` records at a
  configurable cadence — rolling TTFT/ITL p50/p99 over ring reservoirs,
  queue depth, preemption rate, slot occupancy, per-bucket prefill
  counts, and the pool counters — so SLO health is observable MID-run,
  not only from the post-hoc ``serve_summary``. The ITL reservoir is
  fed from the same surfaced-token gaps ``loadgen._summarize`` diffs,
  so windowed and post-hoc percentiles agree on a drained run.
- **Serve-side graftscope** (:func:`profile_serve_programs`): device
  time (``capture_device_profile``), compiled ``cost_analysis``
  flops/bytes, and roofline class for the decode step and every warmed
  prefill bucket, plus ``decode_host_exposed_ms`` — the serving analog
  of ``sync_exposed_ms``: mean live host wall per decode step minus the
  profiled program time, i.e. what the host scheduler costs the decode
  loop.

Spans survive LIFO preemption (``decode_run`` closes, a new ``queue``
span opens at the preempt instant) and kill/resume replay (the fresh
engine's tracer opens ``resume-replay`` admission spans); the engine
feeds the tracer the SAME floats it stamps into ``first_token_time`` /
``token_times``, so queue+prefill span sums reconcile with recorded
TTFT exactly (the <=1 ms acceptance bound is by construction).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any

import numpy as np

__all__ = [
    "PREFILL_KINDS",
    "ServeTracer",
    "check_spans",
    "reconcile",
    "load_trace_dir",
    "render_serve_report",
    "profile_serve_programs",
]

# Admission span kinds: how a request's KV got (re)built in its slot.
PREFILL_KINDS = frozenset({"prefill", "recompute", "resume-replay"})
_INTERVAL_KINDS = PREFILL_KINDS | {"queue", "decode_run"}
# "shed" is terminal like "retire", but for a request REJECTED at
# admission control (serve/guard.py) — it never queued, so its whole
# lifecycle is the one instant. "retire" instants carry a ``status``
# field when the disposition is not "completed" (e.g. "timed_out").
_INSTANT_KINDS = frozenset({"preempt", "retire", "shed"})

TRACE_NAME = "serve_trace.json"
SPANS_NAME = "serve_spans.jsonl"
WINDOWS_NAME = "serve_windows.jsonl"
REQUESTS_NAME = "serve_requests.jsonl"


def _pct(values: Any, q: float) -> float | None:
    vals = np.asarray(list(values), dtype=np.float64)
    return round(float(np.percentile(vals, q)), 3) if vals.size else None


class ServeTracer:
    """Host-side span + SLO-window recorder for one :class:`ServingEngine`.

    The engine calls the ``on_*`` hooks with its own clock stamps; the
    tracer never reads a clock of its own for span endpoints, so spans
    and the engine's latency bookkeeping share the exact same floats.
    ``reset()`` (called by ``run_poisson`` after warmup) drops warmup
    spans so the exported timeline covers only the measured run.

    ``window_every_s`` arms the SLO tracker: ``on_decode_step`` returns
    a flat ``kind:"serve_window"`` record once per cadence interval
    (the engine emits it through its sink); ``flush_window`` emits the
    final partial window at drain. TTFT/ITL percentiles are rolling
    over ``window_capacity``-deep ring reservoirs.
    """

    def __init__(
        self,
        num_slots: int,
        *,
        window_every_s: float | None = None,
        window_capacity: int = 4096,
    ) -> None:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if window_every_s is not None and window_every_s <= 0:
            raise ValueError(
                f"window_every_s must be > 0, got {window_every_s}"
            )
        if window_capacity < 1:
            raise ValueError(
                f"window_capacity must be >= 1, got {window_capacity}"
            )
        self.num_slots = int(num_slots)
        self.window_every_s = window_every_s
        self.window_capacity = int(window_capacity)
        self.reset()

    def reset(self, now: float | None = None) -> None:
        """Drop all recorded state; ``now`` (engine clock) restarts the
        window origin so ``t_s`` counts from the measured run's start."""
        self.spans: list[dict[str, Any]] = []
        self.windows: list[dict[str, Any]] = []
        self.requests: list[dict[str, Any]] = []
        self._open_queue: dict[int, dict[str, Any]] = {}
        self._open_run: dict[int, dict[str, Any]] = {}
        self._t0: float | None = now
        self._last_flush: float | None = now
        self._ttft: deque[float] = deque(maxlen=self.window_capacity)
        self._itl: deque[float] = deque(maxlen=self.window_capacity)
        # (t, live_pages, free_pages, active_slots, queue_depth) at
        # decode-step cadence — the Perfetto counter tracks.
        self._pool_series: deque[tuple] = deque(maxlen=65536)
        self._last_pool: dict[str, Any] = {}
        self._churn_base: int | None = None
        self._trash_base: int | None = None
        self._reset_window_counters()

    def _reset_window_counters(self) -> None:
        self._tokens_w = 0
        self._done_w = 0
        self._preempt_w = 0
        self._steps_w = 0
        self._occ_w = 0
        self._queue_max_w = 0
        self._timeout_w = 0
        self._shed_w = 0
        self._prefill_w: dict[int, int] = {}

    def _seen(self, t: float) -> None:
        if self._t0 is None or t < self._t0:
            self._t0 = float(t)
        if self._last_flush is None:
            self._last_flush = float(t)

    # ------------------------------------------------------ engine hooks

    def on_submit(self, req: Any, now: float) -> None:
        """External submission: open the queue span at the request's
        arrival stamp. A resumed request's preserved ``arrival_time``
        belongs to the dead process's clock epoch, so its queue span
        restarts at the resubmission instant instead."""
        self._seen(float(now))
        if getattr(req, "recovered", False) or req.arrival_time is None:
            t0 = float(now)
        else:
            t0 = min(float(req.arrival_time), float(now))
        self._seen(t0)
        self._open_queue[req.req_id] = {
            "name": "queue", "req": int(req.req_id), "slot": None,
            "t0": t0, "t1": None,
        }

    def on_requeue(self, req: Any, now: float) -> None:
        """Preemption re-queue: a fresh queue span from the preempt
        instant until the recompute admission."""
        self._seen(float(now))
        self._open_queue[req.req_id] = {
            "name": "queue", "req": int(req.req_id), "slot": None,
            "t0": float(now), "t1": None,
        }

    def on_admit(
        self,
        req: Any,
        *,
        slot: int,
        bucket: int,
        t0: float,
        t1: float,
        kind: str,
        replayed: int = 0,
    ) -> None:
        """Admission prefill ran in ``[t0, t1]``; close the queue span
        at ``t0`` (the same float, so queue+prefill tile exactly)."""
        self._seen(float(t0))
        q = self._open_queue.pop(req.req_id, None)
        if q is not None:
            q["t1"] = float(t0)
            self.spans.append(q)
        span = {
            "name": kind, "req": int(req.req_id), "slot": int(slot),
            "bucket": int(bucket), "t0": float(t0), "t1": float(t1),
        }
        if replayed:
            span["replayed"] = int(replayed)
        self.spans.append(span)
        self._prefill_w[int(bucket)] = self._prefill_w.get(int(bucket), 0) + 1
        self._tokens_w += 1  # prefill surfaces the first token

    def _close_run(self, slot: int) -> None:
        run = self._open_run.pop(slot, None)
        if run is not None:
            self.spans.append(run)

    def on_decode_step(
        self,
        t0: float,
        t1: float,
        slot_reqs: dict[int, int],
        pool: dict[str, Any],
        queue_depth: int,
    ) -> dict[str, Any] | None:
        """One fixed-shape decode step over ``slot_reqs`` (slot ->
        req_id) ran in ``[t0, t1]``. Extends each slot's coalesced
        ``decode_run`` span, samples the pool counter series, and
        returns a ``serve_window`` record when the cadence elapsed."""
        self._seen(float(t0))
        for slot, rid in slot_reqs.items():
            run = self._open_run.get(slot)
            if run is None or run["req"] != rid:
                self._close_run(slot)  # missed retire — defensive close
                run = {
                    "name": "decode_run", "req": int(rid), "slot": int(slot),
                    "t0": float(t0), "t1": float(t1), "tokens": 0,
                }
                self._open_run[slot] = run
            run["t1"] = float(t1)
            run["tokens"] += 1
        self._steps_w += 1
        self._occ_w += len(slot_reqs)
        self._tokens_w += len(slot_reqs)
        self._queue_max_w = max(self._queue_max_w, int(queue_depth))
        if self._churn_base is None:
            self._churn_base = int(pool.get("churn", 0))
            self._trash_base = int(pool.get("trash", 0))
        self._last_pool = dict(pool)
        self._pool_series.append((
            float(t1), int(pool.get("live", 0)), int(pool.get("free", 0)),
            len(slot_reqs), int(queue_depth),
        ))
        if self.window_every_s is None or self._last_flush is None:
            return None
        if (float(t1) - self._last_flush) < self.window_every_s:
            return None
        return self.flush_window(float(t1), queue_depth=int(queue_depth))

    def on_preempt(self, req: Any, slot: int, now: float, replayed: int) -> None:
        self._seen(float(now))
        self._close_run(slot)
        self.spans.append({
            "name": "preempt", "req": int(req.req_id), "slot": int(slot),
            "t0": float(now), "t1": float(now), "replayed": int(replayed),
        })
        self._preempt_w += 1

    def on_crash(self, now: float) -> None:
        """Engine death under supervised recovery (serve/guard.py):
        seal every open span at the crash instant. The tracer outlives
        the engine generation, so without this the next generation's
        first decode step would extend the dead slots' open runs to
        post-resume timestamps, overlapping the resumed requests' new
        queue spans."""
        self._seen(float(now))
        for slot in sorted(self._open_run):
            self._close_run(slot)  # t1 already stamped at the last step
        for q in self._open_queue.values():
            q["t1"] = float(now)
            self.spans.append(q)
        self._open_queue.clear()

    def on_shed(self, req: Any, now: float, reason: str) -> None:
        """Terminal rejection at admission control (serve/guard.py):
        the request never queued, so its whole lifecycle is this one
        ``shed`` instant."""
        self._seen(float(now))
        self.spans.append({
            "name": "shed", "req": int(req.req_id), "slot": None,
            "t0": float(now), "t1": float(now), "reason": str(reason),
        })
        self._shed_w += 1
        self.requests.append({
            "req": int(req.req_id),
            "status": "rejected",
            "reason": str(reason),
            "tokens": 0,
            "preemptions": 0,
            "recovered": False,
        })

    def on_retire(self, req: Any, slot: int | None, now: float) -> None:
        self._seen(float(now))
        if slot is not None:
            self._close_run(slot)
        q = self._open_queue.pop(req.req_id, None)
        if q is not None:  # finished while queued (budget spent at preempt)
            q["t1"] = float(now)
            self.spans.append(q)
        retire_span: dict[str, Any] = {
            "name": "retire", "req": int(req.req_id),
            "slot": None if slot is None else int(slot),
            "t0": float(now), "t1": float(now),
        }
        status = getattr(req, "status", None)
        if status not in (None, "completed"):
            retire_span["status"] = status
        if status == "timed_out":
            self._timeout_w += 1
        self.spans.append(retire_span)
        self._done_w += 1
        rec: dict[str, Any] = {
            "req": int(req.req_id),
            "tokens": int(req.output_tokens),
            "preemptions": int(req.preemptions),
            "recovered": bool(getattr(req, "recovered", False)),
        }
        if status not in (None, "completed"):
            rec["status"] = status
        if req.first_token_time is not None and req.arrival_time is not None:
            rec["ttft_ms"] = (req.first_token_time - req.arrival_time) * 1e3
        if len(req.token_times) > 1:
            rec["stream_ms"] = (
                req.token_times[-1] - req.token_times[0]
            ) * 1e3
        self.requests.append(rec)

    def sample_ttft(self, ms: float, now: float) -> None:
        self._seen(float(now))
        self._ttft.append(float(ms))

    def sample_itl(self, ms: float, now: float) -> None:
        self._seen(float(now))
        self._itl.append(float(ms))

    # ------------------------------------------------------ SLO windows

    def flush_window(
        self, now: float, *, queue_depth: int = 0
    ) -> dict[str, Any] | None:
        """Emit one flat ``serve_window`` record covering everything
        since the previous flush (rolling percentiles over the full
        reservoirs; counters are per-window). Returns None before any
        recorded activity."""
        if self._t0 is None:
            return None
        if self._last_flush is None:
            self._last_flush = self._t0
        width = max(1e-9, float(now) - self._last_flush)
        pool = self._last_pool
        churn = int(pool.get("churn", self._churn_base or 0))
        trash = int(pool.get("trash", self._trash_base or 0))
        rec: dict[str, Any] = {
            "kind": "serve_window",
            "time": time.time(),
            "t_s": round(float(now) - self._t0, 4),
            "window_s": round(width, 4),
            "ttft_p50_ms": _pct(self._ttft, 50),
            "ttft_p99_ms": _pct(self._ttft, 99),
            "itl_p50_ms": _pct(self._itl, 50),
            "itl_p99_ms": _pct(self._itl, 99),
            "ttft_samples": len(self._ttft),
            "itl_samples": len(self._itl),
            "tokens": self._tokens_w,
            "requests_done": self._done_w,
            "decode_steps": self._steps_w,
            "preemptions": self._preempt_w,
            "preempt_rate_per_s": round(self._preempt_w / width, 3),
            "timed_out": self._timeout_w,
            "shed": self._shed_w,
            "queue_depth": int(queue_depth),
            "queue_depth_max": self._queue_max_w,
            "slot_occupancy": round(
                self._occ_w / (self._steps_w * self.num_slots), 4
            ) if self._steps_w else 0.0,
            "live_pages": int(pool.get("live", 0)),
            "free_pages": int(pool.get("free", 0)),
            "page_high_water": int(pool.get("high_water", 0)),
            "page_churn": churn - (self._churn_base or 0),
            "trash_rows": trash - (self._trash_base or 0),
        }
        for bucket, count in sorted(self._prefill_w.items()):
            rec[f"prefill_bucket_{bucket}"] = count
        self.windows.append(rec)
        self._last_flush = float(now)
        self._churn_base = churn
        self._trash_base = trash
        self._reset_window_counters()
        return rec

    # ---------------------------------------------------------- export

    def all_spans(self) -> list[dict[str, Any]]:
        """Closed spans plus a snapshot of still-open decode runs (their
        ``t1`` tracks the latest step end, so they export valid)."""
        return self.spans + [dict(r) for r in self._open_run.values()]

    def to_chrome_trace(self) -> dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable): pid 1 is the
        engine; tid 0 carries the queue's async spans plus the pool
        counter tracks, tids 1..num_slots are the decode-slot lanes."""
        spans = self.all_spans()
        times = [s["t0"] for s in spans] + [t for t, *_ in self._pool_series]
        origin = min(times) if times else 0.0

        def us(t: float) -> float:
            return round((t - origin) * 1e6, 3)

        events: list[dict[str, Any]] = [
            {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
             "args": {"name": "graftserve"}},
            {"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
             "args": {"name": "queue"}},
        ]
        for s in range(self.num_slots):
            events.append({
                "ph": "M", "pid": 1, "tid": s + 1, "name": "thread_name",
                "args": {"name": f"slot {s}"},
            })
        for sp in spans:
            name = sp["name"]
            if name == "queue":
                # Async (b/e) events: queue waits overlap arbitrarily,
                # which a single lane of X events cannot render.
                events.append({
                    "ph": "b", "cat": "queue", "id": sp["req"], "pid": 1,
                    "tid": 0, "name": "queue", "ts": us(sp["t0"]),
                    "args": {"req": sp["req"]},
                })
                if sp["t1"] is not None:
                    events.append({
                        "ph": "e", "cat": "queue", "id": sp["req"],
                        "pid": 1, "tid": 0, "name": "queue",
                        "ts": us(sp["t1"]),
                    })
            elif name in _INSTANT_KINDS:
                tid = 0 if sp.get("slot") is None else sp["slot"] + 1
                events.append({
                    "ph": "i", "s": "t", "pid": 1, "tid": tid,
                    "name": f"{name} r{sp['req']}", "ts": us(sp["t0"]),
                    "args": {"req": sp["req"]},
                })
            else:
                label = (
                    "decode_run" if name == "decode_run"
                    else f"{name}[bucket={sp.get('bucket')}]"
                )
                args = {
                    k: sp[k]
                    for k in ("req", "bucket", "tokens", "replayed")
                    if sp.get(k) is not None
                }
                events.append({
                    "ph": "X", "pid": 1, "tid": sp["slot"] + 1,
                    "name": label, "ts": us(sp["t0"]),
                    "dur": max(0.001, round((sp["t1"] - sp["t0"]) * 1e6, 3)),
                    "args": args,
                })
        for t, live, free, active, depth in self._pool_series:
            events.append({
                "ph": "C", "pid": 1, "tid": 0, "name": "kv_pages",
                "ts": us(t), "args": {"live": live, "free": free},
            })
            events.append({
                "ph": "C", "pid": 1, "tid": 0, "name": "slots_active",
                "ts": us(t), "args": {"active": active},
            })
            events.append({
                "ph": "C", "pid": 1, "tid": 0, "name": "queue_depth",
                "ts": us(t), "args": {"depth": depth},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write(self, trace_dir: str) -> dict[str, str]:
        """Write the trace artifacts; returns name -> path."""
        os.makedirs(trace_dir, exist_ok=True)
        paths = {
            "trace": os.path.join(trace_dir, TRACE_NAME),
            "spans": os.path.join(trace_dir, SPANS_NAME),
            "windows": os.path.join(trace_dir, WINDOWS_NAME),
            "requests": os.path.join(trace_dir, REQUESTS_NAME),
        }
        with open(paths["trace"], "w", encoding="utf-8") as f:
            json.dump(self.to_chrome_trace(), f)
        for key, rows in (
            ("spans", self.all_spans()),
            ("windows", self.windows),
            ("requests", self.requests),
        ):
            with open(paths[key], "w", encoding="utf-8") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
        return paths


# ---------------------------------------------------------------------------
# Consistency checks — the CI gate over a written trace
# ---------------------------------------------------------------------------


def check_spans(
    spans: list[dict[str, Any]], *, require_retired: bool = True
) -> list[str]:
    """Structural audit of a span list; returns human-readable problem
    strings (empty = consistent). Checks: every span closed and
    well-ordered, per-request interval spans never overlap, lifecycles
    start with a queue span, every admission span follows a queue span,
    exactly one retire per request (none extends past it), and — with
    ``require_retired`` — no orphans (requests that never retired)."""
    problems: list[str] = []
    by_req: dict[int, list[dict[str, Any]]] = {}
    for sp in spans:
        by_req.setdefault(sp.get("req"), []).append(sp)
    for rid in sorted(by_req, key=lambda r: (r is None, r)):
        sps = by_req[rid]
        for sp in sps:
            if sp.get("t1") is None:
                problems.append(f"req {rid}: unclosed {sp['name']} span")
            elif sp["t1"] < sp["t0"] - 1e-9:
                problems.append(
                    f"req {rid}: {sp['name']} span ends before it starts"
                )
        closed = sorted(
            (s for s in sps
             if s["name"] in _INTERVAL_KINDS and s.get("t1") is not None),
            key=lambda s: (s["t0"], s["t1"]),
        )
        for a, b in zip(closed, closed[1:]):
            if b["t0"] < a["t1"] - 1e-6:
                problems.append(
                    f"req {rid}: {a['name']} and {b['name']} spans overlap"
                )
        if closed and closed[0]["name"] != "queue":
            problems.append(
                f"req {rid}: lifecycle starts with {closed[0]['name']}, "
                "expected queue"
            )
        for i, sp in enumerate(closed):
            if sp["name"] in PREFILL_KINDS and (
                i == 0 or closed[i - 1]["name"] != "queue"
            ):
                problems.append(
                    f"req {rid}: {sp['name']} not preceded by a queue span"
                )
        retires = [s for s in sps if s["name"] == "retire"]
        sheds = [s for s in sps if s["name"] == "shed"]
        if len(retires) > 1:
            problems.append(f"req {rid}: {len(retires)} retire instants")
        if sheds and (retires or closed):
            # Shed happens at admission control, before the request ever
            # queues — a shed lifecycle is exactly one instant.
            problems.append(
                f"req {rid}: shed request has other lifecycle spans"
            )
        if not retires:
            if require_retired and not sheds:
                problems.append(f"req {rid}: never retired (orphan spans)")
        else:
            if closed:
                last_end = max(s["t1"] for s in closed)
                if retires[0]["t0"] < last_end - 1e-6:
                    problems.append(
                        f"req {rid}: spans extend past the retire instant"
                    )
            if (
                not any(s["name"] in PREFILL_KINDS for s in closed)
                and retires[0].get("status") != "timed_out"
            ):
                # A queued-expiry retire legitimately has only a closed
                # queue span: the request never reached a slot.
                problems.append(
                    f"req {rid}: retired without an admission span"
                )
    return problems


def reconcile(
    spans: list[dict[str, Any]],
    requests: list[dict[str, Any]],
    *,
    tol_ms: float = 1.0,
) -> list[str]:
    """Cross-check span arithmetic against the engine-recorded latency
    numbers: per request, (first admission end - first queue start) must
    equal the recorded TTFT, and the post-first-token spans must fit
    inside the recorded token stream. Recovered requests are skipped —
    their preserved stamps belong to the dead process's clock epoch."""
    problems: list[str] = []
    by_req: dict[int, list[dict[str, Any]]] = {}
    for sp in spans:
        if sp["name"] in _INTERVAL_KINDS and sp.get("t1") is not None:
            by_req.setdefault(sp["req"], []).append(sp)
    for rec in requests:
        if rec.get("recovered"):
            continue
        if rec.get("status") in ("rejected", "timed_out"):
            # Shed at admission (no spans at all) or expired before the
            # first token (no admission span / no TTFT) — nothing to
            # reconcile against.
            continue
        rid = rec["req"]
        sps = sorted(by_req.get(rid, []), key=lambda s: s["t0"])
        queues = [s for s in sps if s["name"] == "queue"]
        admits = [s for s in sps if s["name"] in PREFILL_KINDS]
        if not queues or not admits:
            problems.append(f"req {rid}: no queue/admission span to reconcile")
            continue
        ttft = rec.get("ttft_ms")
        if ttft is not None:
            span_ttft = (admits[0]["t1"] - queues[0]["t0"]) * 1e3
            if abs(span_ttft - ttft) > tol_ms:
                problems.append(
                    f"req {rid}: queue+prefill spans sum to "
                    f"{span_ttft:.3f} ms but recorded TTFT is "
                    f"{ttft:.3f} ms"
                )
        stream = rec.get("stream_ms")
        if stream is not None:
            first_end = admits[0]["t1"]
            covered = sum(
                (s["t1"] - max(s["t0"], first_end)) * 1e3
                for s in sps
                if s["t1"] > first_end
            )
            if covered > stream + tol_ms:
                problems.append(
                    f"req {rid}: {covered:.3f} ms of post-first-token "
                    f"spans exceed the {stream:.3f} ms token stream"
                )
    return problems


# ---------------------------------------------------------------------------
# Trace-dir loading + report rendering (obs __main__ serve-report)
# ---------------------------------------------------------------------------


def _load_jsonl(path: str) -> list[dict[str, Any]]:
    rows: list[dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict):
                rows.append(row)
    return rows


def load_trace_dir(path: str) -> dict[str, list[dict[str, Any]]]:
    """Load a graftserve trace dir (or a bare spans JSONL) into
    ``{"spans": [...], "windows": [...], "requests": [...]}``."""
    if os.path.isdir(path):
        out = {}
        for key, name in (
            ("spans", SPANS_NAME),
            ("windows", WINDOWS_NAME),
            ("requests", REQUESTS_NAME),
        ):
            p = os.path.join(path, name)
            out[key] = _load_jsonl(p) if os.path.exists(p) else []
        if not out["spans"]:
            raise FileNotFoundError(f"{path}: no {SPANS_NAME}")
        return out
    return {"spans": _load_jsonl(path), "windows": [], "requests": []}


def render_serve_report(data: dict[str, list[dict[str, Any]]]) -> str:
    """One-screen text summary of a loaded trace dir."""
    spans = data.get("spans", [])
    windows = data.get("windows", [])
    requests = data.get("requests", [])
    counts: dict[str, int] = {}
    for sp in spans:
        counts[sp.get("name", "?")] = counts.get(sp.get("name", "?"), 0) + 1
    rows = [
        ("spans", str(len(spans))),
        ("span kinds", ", ".join(
            f"{k}={v}" for k, v in sorted(counts.items())
        ) or "-"),
        ("requests", str(len({s.get("req") for s in spans}))),
        ("retired", str(counts.get("retire", 0))),
        ("shed", str(counts.get("shed", 0))),
        ("timed out", str(sum(
            1 for r in requests if r.get("status") == "timed_out"
        ))),
        ("recovered", str(sum(1 for r in requests if r.get("recovered")))),
        ("windows", str(len(windows))),
    ]
    if windows:
        last = windows[-1]
        rows.append(("ttft p99 (last window)",
                     f"{last.get('ttft_p99_ms')} ms"))
        rows.append(("itl p99 (last window)",
                     f"{last.get('itl_p99_ms')} ms"))
        rows.append(("live pages (peak)", str(max(
            (w.get("live_pages", 0) for w in windows), default=0
        ))))
        rows.append(("queue depth (max)", str(max(
            (w.get("queue_depth_max", 0) for w in windows), default=0
        ))))
    width = max(len(name) for name, _ in rows)
    return "\n".join(f"{name:<{width}}  {val}" for name, val in rows)


# ---------------------------------------------------------------------------
# Serve-side graftscope: device time + cost analysis for the programs
# ---------------------------------------------------------------------------


def profile_serve_programs(
    engine: Any, *, iters: int = 3
) -> list[dict[str, Any]]:
    """Attribute device time, compiled flops/bytes, and roofline class
    to the engine's decode step and every warmed prefill bucket.

    Run this AFTER the serving run (it re-executes the programs under a
    profiler trace and AOT-compiles for ``cost_analysis`` — both would
    pollute a CompileCounter-gated section). The engine's programs
    donate their pages argument, so each profiled run works on a fresh
    copy of the pools and rebinds between calls — the live engine state
    is never consumed.

    Returns flat ``kind:"serve_phase"`` records (one per program) plus
    one ``kind:"serve_phase_summary"`` carrying
    ``decode_host_exposed_ms``: mean host wall per LIVE decode step
    (engine-recorded) minus the profiled program time — the host
    scheduling overhead a decode token actually pays, the serving
    analog of graftscope's ``sync_exposed_ms``.
    """
    import jax
    import jax.numpy as jnp

    from .phases import (
        capture_device_profile,
        compiled_costs,
        roofline_classify,
    )

    cfg = engine.cfg
    b, p = cfg.num_slots, cfg.max_pages_per_slot
    device_kind = getattr(jax.devices()[0], "device_kind", None)

    def fresh_pages():
        # x + 0 allocates a new buffer with the same sharding — the
        # programs donate their pages argument, so profiling must not
        # hand them the engine's live pools.
        return jax.tree.map(lambda x: x + 0, engine._pages)

    # A ServeChaosMonkey wraps _decode_step in a plain function; unwrap
    # to the jitted original — for .lower(), and so profiling re-runs
    # never advance the monkey's fault counter.
    decode_step = getattr(
        engine._decode_step, "__wrapped__", engine._decode_step
    )

    key = engine._sample_root
    dec_args = (
        jnp.zeros((b,), jnp.int32),
        jnp.ones((b,), jnp.int32),
        jnp.zeros((b, p), jnp.int32),
        jnp.ones((b,), jnp.bool_),
        jnp.arange(b, dtype=jnp.int32),
        jnp.zeros((b,), jnp.int32),
        key,
    )

    def _runner(fn, args, state):
        def run():
            state["pages"], out = fn(engine.params, state["pages"], *args)
            return out
        return run

    records: list[dict[str, Any]] = []
    dec_state = {"pages": fresh_pages()}
    prof = capture_device_profile(
        _runner(decode_step, dec_args, dec_state), iters=iters
    )
    costs = compiled_costs(
        decode_step.lower(
            engine.params, dec_state["pages"], *dec_args
        ).compile()
    )
    records.append({
        "kind": "serve_phase",
        "time": time.time(),
        "phase": "decode",
        "impl": engine.paged_attention_impl,
        "clock": prof.clock,
        "device_ms": round(prof.device_ms, 4),
        "wall_ms": round(prof.wall_ms, 4),
        "flops": costs["flops"],
        "bytes_accessed": costs["bytes_accessed"],
        "roofline": roofline_classify(
            costs["flops"], costs["bytes_accessed"], device_kind
        ),
        "iters": iters,
    })
    decode_ms = prof.best_ms()
    for bucket in sorted(engine._prefill_cache):
        fn = engine._prefill_cache[bucket]
        plen = min(bucket, engine.max_seq_len - 1)
        pf_args = (
            jnp.ones((1, bucket), jnp.int32),
            jnp.int32(plen),
            jnp.zeros((p,), jnp.int32),
            key,
        )
        state = {"pages": fresh_pages()}
        prof_b = capture_device_profile(
            _runner(fn, pf_args, state), iters=iters
        )
        costs_b = compiled_costs(
            fn.lower(engine.params, state["pages"], *pf_args).compile()
        )
        records.append({
            "kind": "serve_phase",
            "time": time.time(),
            "phase": f"prefill[bucket={bucket}]",
            "impl": engine.paged_attention_impl,
            "bucket": bucket,
            "clock": prof_b.clock,
            "device_ms": round(prof_b.device_ms, 4),
            "wall_ms": round(prof_b.wall_ms, 4),
            "flops": costs_b["flops"],
            "bytes_accessed": costs_b["bytes_accessed"],
            "roofline": roofline_classify(
                costs_b["flops"], costs_b["bytes_accessed"], device_kind
            ),
            "iters": iters,
        })
    walls = [float(w) for w in engine._decode_walls]
    summary: dict[str, Any] = {
        "kind": "serve_phase_summary",
        "time": time.time(),
        "impl": engine.paged_attention_impl,
        "decode_step_ms": round(decode_ms, 4),
        "decode_clock": prof.clock,
        "decode_steps_observed": len(walls),
    }
    if walls:
        mean_wall_ms = sum(walls) / len(walls) * 1e3
        summary["decode_host_ms"] = round(mean_wall_ms, 4)
        summary["decode_host_exposed_ms"] = round(
            max(0.0, mean_wall_ms - decode_ms), 4
        )
    records.append(summary)
    return records
