"""Unified telemetry: on-device metrics, pluggable sinks, run
manifests, and HBM/MFU accounting.

Entry point for engines and CLIs is :class:`Telemetry`; everything
else (sinks, flops models, manifests, system monitors) is importable
from its submodule for tools that only need one piece.
"""

from .metrics import (
    Telemetry,
    expert_load_entropy,
    sown_scalar_mean,
    speculative_accept_rate,
    tree_l2_norm,
    tree_sq_norm,
)
from .fleet import (
    ClockAligner,
    FleetStamper,
    collective_skew,
    fleet_check,
    load_fleet_dir,
    merge_timeline,
    render_fleet_report,
    write_fleet_artifacts,
)
from .flight import FlightRecorder, HbmHighWater, StragglerMonitor
from .phases import (
    PhaseReport,
    PhaseStat,
    capture_device_profile,
    phase_records_from_stream,
    profile_lm_phases,
    profile_phases,
    render_phase_table,
)
from .run_manifest import build_manifest, read_manifest, write_manifest
from .serve_trace import (
    ServeTracer,
    check_spans,
    profile_serve_programs,
    reconcile,
)
from .sinks import (
    CsvSink,
    JsonlSink,
    MetricSink,
    MultiSink,
    NullSink,
    RingSink,
    StreamSink,
    rank_zero,
    sanitize,
)
from .system import CompileCounter, SystemMonitor, hbm_stats
from . import flops

__all__ = [
    "Telemetry",
    "expert_load_entropy",
    "sown_scalar_mean",
    "speculative_accept_rate",
    "tree_l2_norm",
    "tree_sq_norm",
    "ClockAligner",
    "FleetStamper",
    "collective_skew",
    "fleet_check",
    "load_fleet_dir",
    "merge_timeline",
    "render_fleet_report",
    "write_fleet_artifacts",
    "FlightRecorder",
    "HbmHighWater",
    "StragglerMonitor",
    "PhaseReport",
    "PhaseStat",
    "capture_device_profile",
    "phase_records_from_stream",
    "profile_lm_phases",
    "profile_phases",
    "render_phase_table",
    "build_manifest",
    "read_manifest",
    "write_manifest",
    "ServeTracer",
    "check_spans",
    "profile_serve_programs",
    "reconcile",
    "CsvSink",
    "JsonlSink",
    "MetricSink",
    "MultiSink",
    "NullSink",
    "RingSink",
    "StreamSink",
    "rank_zero",
    "sanitize",
    "CompileCounter",
    "SystemMonitor",
    "hbm_stats",
    "flops",
]
