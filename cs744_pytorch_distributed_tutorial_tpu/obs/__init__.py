"""Unified telemetry: on-device metrics, pluggable sinks, run
manifests, and HBM/MFU accounting.

Entry point for engines and CLIs is :class:`Telemetry`; everything
else (sinks, flops models, manifests, system monitors) is importable
from its submodule for tools that only need one piece.
"""

from .metrics import (
    Telemetry,
    expert_load_entropy,
    sown_scalar_mean,
    speculative_accept_rate,
    tree_l2_norm,
    tree_sq_norm,
)
from .run_manifest import build_manifest, read_manifest, write_manifest
from .sinks import (
    CsvSink,
    JsonlSink,
    MetricSink,
    MultiSink,
    NullSink,
    RingSink,
    StreamSink,
    rank_zero,
    sanitize,
)
from .system import CompileCounter, SystemMonitor, hbm_stats
from . import flops

__all__ = [
    "Telemetry",
    "expert_load_entropy",
    "sown_scalar_mean",
    "speculative_accept_rate",
    "tree_l2_norm",
    "tree_sq_norm",
    "build_manifest",
    "read_manifest",
    "write_manifest",
    "CsvSink",
    "JsonlSink",
    "MetricSink",
    "MultiSink",
    "NullSink",
    "RingSink",
    "StreamSink",
    "rank_zero",
    "sanitize",
    "CompileCounter",
    "SystemMonitor",
    "hbm_stats",
    "flops",
]
