"""Run-start manifests.

A metrics JSONL on its own is a pile of numbers; the manifest written
next to it (``manifest.json``) is what makes the stream
self-describing: the exact config dataclass, mesh shape, device
kind/count, jax/jaxlib versions, and — when the repo is a git
checkout — the commit SHA. Post-mortems and benchmark sweeps join on
this file, never on directory-naming conventions.

Only process 0 writes on multihost (same replicated information on
every host), and the write is atomic (tmp + ``os.replace``) so a
crash mid-run never leaves a half-written manifest beside a valid
metrics file.
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import os
import platform
import subprocess
import sys
from typing import Any, Mapping

__all__ = ["build_manifest", "write_manifest", "read_manifest"]

MANIFEST_NAME = "manifest.json"


def _git_sha() -> str | None:
    """Commit SHA of the repo this package lives in, or None when not
    a git checkout / git absent (installed wheels, containers)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=5,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def _config_dict(config: Any) -> Any:
    if config is None:
        return None
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        raw = dataclasses.asdict(config)
        # Keep the manifest strict-JSON: tuples become lists via json,
        # but exotic leaves (dtypes, paths) need a str fallback.
        return json.loads(json.dumps(raw, default=str))
    if isinstance(config, Mapping):
        return json.loads(json.dumps(dict(config), default=str))
    return str(config)


def _mesh_dict(mesh: Any) -> dict[str, int] | None:
    if mesh is None:
        return None
    try:
        return {str(name): int(size) for name, size in mesh.shape.items()}
    except (AttributeError, TypeError):
        return None


def build_manifest(
    config: Any = None, mesh: Any = None, **extra: Any
) -> dict[str, Any]:
    """Assemble the manifest dict. Everything is best-effort: a
    manifest with a null field beats a run with no manifest."""
    import jax

    try:
        devices = jax.devices()
        device_kind = devices[0].device_kind
        backend = jax.default_backend()
        n_devices = len(devices)
        n_local = jax.local_device_count()
    except RuntimeError:
        devices, device_kind, backend, n_devices, n_local = [], None, None, 0, 0
    try:
        n_processes = jax.process_count()
        process_index = jax.process_index()
    except RuntimeError:
        n_processes, process_index = 1, 0

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None)
    except ImportError:
        jaxlib_version = None

    manifest: dict[str, Any] = {
        "kind": "manifest",
        "created_at": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "argv": list(sys.argv),
        "python_version": platform.python_version(),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
        "backend": backend,
        "device_kind": device_kind,
        "device_count": n_devices,
        "local_device_count": n_local,
        "process_count": n_processes,
        "process_index": process_index,
        "hostname": platform.node(),
        "git_sha": _git_sha(),
        "mesh": _mesh_dict(mesh),
        "config": _config_dict(config),
    }
    manifest.update(extra)
    return manifest


def write_manifest(
    path: str, config: Any = None, mesh: Any = None, **extra: Any
) -> str | None:
    """Write ``manifest.json`` under directory ``path`` (or to ``path``
    itself when it ends in .json). Returns the file path, or None on
    non-zero ranks. Atomic so readers never see a torn file."""
    import jax

    try:
        if jax.process_index() != 0:
            return None
    except RuntimeError:
        pass  # backend not up yet: single-process, write away

    if path.endswith(".json"):
        target = path
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)
        target = os.path.join(path, MANIFEST_NAME)
    manifest = build_manifest(config=config, mesh=mesh, **extra)
    tmp = target + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, target)
    return target


def read_manifest(path: str) -> dict[str, Any]:
    """Load a manifest from a file or from the directory holding it."""
    if os.path.isdir(path):
        path = os.path.join(path, MANIFEST_NAME)
    with open(path, encoding="utf-8") as f:
        return json.load(f)
