"""Pluggable metric sinks.

One protocol — ``MetricSink.emit(record) / close()`` — and a small set
of concrete sinks behind it:

- :class:`JsonlSink`: newline-delimited JSON, the default on-disk
  format. Every record is flushed on write so a wedged or killed run
  still leaves a readable file (the watchdog depends on this).
- :class:`CsvSink`: spreadsheet-friendly; the header is frozen by the
  FIRST record emitted (later records with extra keys have those keys
  dropped, missing keys become empty cells) so the file stays
  rectangular no matter what mixture of record kinds flows through.
- :class:`RingSink`: bounded in-memory deque — the tail the watchdog
  flushes when a step wedges, and what tests assert against.
- :class:`MultiSink` / :class:`NullSink` / :class:`StreamSink`:
  fan-out, no-op, and write-to-stream (``bench.py`` uses the stream
  sink to keep printing its one-line JSON to stdout through the same
  schema path as training telemetry).

``rank_zero(sink)`` wraps any sink so only process 0 writes on
multihost — every process computes the same replicated scalars, so
writing from all of them would only duplicate rows.

JSON does not allow ``NaN``/``Infinity`` literals; non-finite floats
are sanitized to ``None`` (JSON ``null``) at emission so a diverged
run produces a *parseable* record stream, not a corrupt one.
"""

from __future__ import annotations

import csv
import io
import json
import math
import threading
from collections import deque
from typing import Any, Iterable, Mapping, Protocol, runtime_checkable

__all__ = [
    "MetricSink",
    "JsonlSink",
    "CsvSink",
    "RingSink",
    "MultiSink",
    "NullSink",
    "StreamSink",
    "rank_zero",
    "sanitize",
]


@runtime_checkable
class MetricSink(Protocol):
    """Anything that accepts flat metric records (str -> scalar/str)."""

    def emit(self, record: Mapping[str, Any]) -> None: ...

    def close(self) -> None: ...


def sanitize(record: Mapping[str, Any]) -> dict[str, Any]:
    """Flatten a record to JSON-safe python scalars.

    Non-finite floats become ``None`` — strict JSON has no ``NaN``
    token, and a diverged loss must not corrupt the stream the
    post-mortem depends on. Numpy/JAX 0-d scalars are coerced via
    ``float()``/``int()`` by json itself; anything unknown falls back
    to ``str``.
    """
    out: dict[str, Any] = {}
    for k, v in record.items():
        if isinstance(v, float) and not math.isfinite(v):
            out[k] = None
        elif isinstance(v, (str, int, bool)) or v is None:
            out[k] = v
        elif isinstance(v, float):
            out[k] = v
        else:
            # Numpy scalars, 0-d arrays, dtypes, paths, ...
            try:
                f = float(v)
            except (TypeError, ValueError):
                out[k] = str(v)
            else:
                out[k] = f if math.isfinite(f) else None
    return out


class JsonlSink:
    """Append-mode newline-delimited JSON with per-record flush."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def emit(self, record: Mapping[str, Any]) -> None:
        line = json.dumps(sanitize(record), allow_nan=False)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class CsvSink:
    """CSV with the header frozen at the first emitted record.

    Keys absent from a later record write as empty cells; keys the
    first record didn't have are dropped — a CSV cannot grow columns
    after the fact, and a stable header is exactly what makes the file
    loadable into pandas/sheets without surgery.
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8", newline="")
        self._writer: csv.DictWriter | None = None
        self._lock = threading.Lock()

    def emit(self, record: Mapping[str, Any]) -> None:
        rec = sanitize(record)
        with self._lock:
            if self._writer is None:
                self._writer = csv.DictWriter(
                    self._f, fieldnames=list(rec), extrasaction="ignore",
                    restval="",
                )
                self._writer.writeheader()
            self._writer.writerow(rec)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class RingSink:
    """Thread-safe bounded ring of the most recent records."""

    def __init__(self, capacity: int = 256):
        if capacity <= 0:
            raise ValueError(f"ring capacity must be positive, got {capacity}")
        self.capacity = capacity
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def emit(self, record: Mapping[str, Any]) -> None:
        with self._lock:
            self._ring.append(sanitize(record))

    def records(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        pass


class MultiSink:
    """Fan one emit out to several sinks."""

    def __init__(self, sinks: Iterable[MetricSink]):
        self.sinks = list(sinks)

    def emit(self, record: Mapping[str, Any]) -> None:
        for s in self.sinks:
            s.emit(record)

    def close(self) -> None:
        for s in self.sinks:
            s.close()


class NullSink:
    """Swallows everything. The no-telemetry default."""

    def emit(self, record: Mapping[str, Any]) -> None:
        pass

    def close(self) -> None:
        pass


class StreamSink:
    """One JSON line per record to an arbitrary text stream.

    ``bench.py`` routes its stdout JSON through this so benchmark
    output and training telemetry share one serialization path (same
    sanitization, same schema fields).
    """

    def __init__(self, stream: io.TextIOBase):
        self.stream = stream

    def emit(self, record: Mapping[str, Any]) -> None:
        self.stream.write(json.dumps(sanitize(record), allow_nan=False) + "\n")
        self.stream.flush()

    def close(self) -> None:
        pass  # never close a borrowed stream (it is usually stdout)


def rank_zero(sink: MetricSink) -> MetricSink:
    """Gate a sink to process 0 on multihost; pass-through otherwise.

    Evaluated lazily per-emit: ``jax.distributed`` may initialize
    *after* telemetry is constructed, and process index is cheap to
    read (cf. the ``utils/logging`` prefix bug this PR also fixes —
    never cache process identity at construction time).
    """
    return _RankZeroSink(sink)


class _RankZeroSink:
    def __init__(self, inner: MetricSink):
        self.inner = inner

    @staticmethod
    def _is_rank0() -> bool:
        import jax

        try:
            return jax.process_index() == 0
        except RuntimeError:  # backend not initialized yet
            return True

    def emit(self, record: Mapping[str, Any]) -> None:
        if self._is_rank0():
            self.inner.emit(record)

    def close(self) -> None:
        self.inner.close()
