"""graftfleet: cross-process timeline aggregation and incident audit.

Everything before this module is per-process: each rank writes its own
metrics/events JSONL (PR 2/6), the elastic supervisor writes
``events.jsonl`` + heartbeat/death-note/world files (PR 14), and the
Perfetto export (PR 13) covers a single serving engine. graftfleet is
the merge layer — it ingests a whole rendezvous-store directory and
produces one clock-aligned view of the run:

- **Merged Perfetto timeline** (``merge_timeline``): one lane per
  process (global rank, stable across generations), a generation track,
  step and collective spans per rank, and instant markers for chaos
  injections, missed heartbeats, death notes, re-elections, and
  re-execs. Open ``fleet_trace.json`` in https://ui.perfetto.dev.
- **Collective-skew attribution** (``collective_skew``): each rank
  stamps step-boundary and sync-entry/exit (wall, monotonic) pairs into
  its stream (``FleetStamper``; the engines piggyback the stamps on
  their cadence-gated fetch, so no new host syncs — GL009-clean). The
  merger aligns per-process clocks via the rendezvous-barrier handshake
  (``ClockAligner`` over ``RendezvousStore.barrier_stamp`` anchors),
  then reports per-step ``collective_wait_ms`` per rank and names the
  straggler — the rank whose late arrival the others waited on. The
  MAD monitor in ``obs/flight.py`` sees only its own process; this is
  the cross-rank view it cannot have.
- **Incident-consistency audit** (``fleet_check``): every death pairs
  with a re-election and a re-exec into g+1, no orphan generations, no
  step span crosses a generation seal, stamps are internally ordered —
  the multihost analog of graftserve's ``check_spans``.

``python -m …obs fleet-report <store_dir> [--check]`` is the CLI;
``launch.py``'s supervisor calls ``write_fleet_artifacts`` at exit so
every elastic run leaves ``fleet_trace.json`` + ``fleet_report.json``
behind without anyone asking.

Clock model: ``attach()`` stamps (wall, monotonic) on every rank the
moment ``mesh.initialize`` returns — all ranks leave the rendezvous
barrier near-simultaneously, so those stamps anchor each rank's
monotonic clock to one shared instant. A record stamped ``(wall,
mono)`` on rank r in generation g maps to the reference timeline as
``ref_anchor_wall + (mono - anchor_mono[r])`` — monotonic elapsed since
the barrier, laid onto the reference rank's wall clock. That holds
across machines (each mono is only ever differenced against the same
machine's anchor) and is immune to wall steps mid-run; records without
a monotonic stamp fall back to wall time corrected by the anchor-wall
offset.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Iterable, Mapping

FLEET_DIRNAME = "fleet"
TRACE_NAME = "fleet_trace.json"
REPORT_NAME = "fleet_report.json"

# Store events attributed to the supervisor process: rendered on the
# fleet lane (their runtime labels carry the supervisor's identity, not
# a worker's — placing them on "rank 0" would lie).
SUPERVISOR_EVENTS = frozenset(
    {
        "generation_start",
        "worker_death",
        "worker_exit",
        "reelection",
        "run_complete",
        "recovery_giveup",
    }
)


def stamp_pair() -> tuple[float, float]:
    """(wall, monotonic) sampled back-to-back — the unit every fleet
    stamp is made of."""
    return time.time(), time.monotonic()


# -------------------------------------------------------------- stamper
class FleetStamper:
    """Per-rank step/sync stamp stream under ``<store>/fleet/``.

    One writer per file (the rank itself), one JSON line per completed
    step carrying four (wall, mono) pairs::

        {"kind": "fleet_stamp", "generation": 0, "global_rank": 3,
         "step": 7,
         "step_enter_wall": …, "step_enter_mono": …,
         "sync_enter_wall": …, "sync_enter_mono": …,   # arrived at the
         "sync_exit_wall": …,  "sync_exit_mono": …,    # blocking fetch
         "step_exit_wall": …,  "step_exit_mono": …}

    ``sync_enter`` is the rank's ARRIVAL at the step's synchronous
    section — stamped after all per-rank host work (including any
    injected stall) and immediately before the first call that can
    block on peers — and ``sync_exit`` is taken right after the step's
    blocking fetch returns. Where the wait actually lands between those
    two varies by backend (cross-process CPU collectives block at
    dispatch; TPU async dispatch blocks at the fetch), but the window
    brackets it either way. Aligned across ranks, the enter spread IS
    the collective skew: early ranks sit inside the window waiting for
    the straggler, and every rank leaves it near-simultaneously. A step
    that never completes (its rank died or exited mid-step) leaves no
    record — the audit counts on that.
    """

    def __init__(
        self,
        root: str,
        generation: int,
        global_rank: int,
        process_id: int | None = None,
    ):
        self.generation = int(generation)
        self.global_rank = int(global_rank)
        self.process_id = process_id
        fleet_dir = os.path.join(os.path.abspath(root), FLEET_DIRNAME)
        os.makedirs(fleet_dir, exist_ok=True)
        self.path = os.path.join(
            fleet_dir, f"g{self.generation:06d}_r{self.global_rank}.jsonl"
        )
        self._f = open(self.path, "a", encoding="utf-8")

    def stamp_step(
        self,
        step: int,
        *,
        step_enter: tuple[float, float],
        sync_enter: tuple[float, float],
        sync_exit: tuple[float, float],
        step_exit: tuple[float, float],
    ) -> None:
        record: dict[str, Any] = {
            "kind": "fleet_stamp",
            "generation": self.generation,
            "global_rank": self.global_rank,
            "step": int(step),
        }
        if self.process_id is not None:
            record["process_id"] = int(self.process_id)
        for name, (wall, mono) in (
            ("step_enter", step_enter),
            ("sync_enter", sync_enter),
            ("sync_exit", sync_exit),
            ("step_exit", step_exit),
        ):
            record[f"{name}_wall"] = wall
            record[f"{name}_mono"] = mono
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "FleetStamper":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# --------------------------------------------------------------- ingest
@dataclasses.dataclass
class FleetData:
    """Everything a multi-process run left behind, parsed."""

    root: str
    worlds: dict[int, dict[str, Any]]
    events: list[dict[str, Any]]
    stamps: list[dict[str, Any]]
    barrier_stamps: dict[int, dict[int, dict[str, Any]]]
    heartbeats: dict[tuple[int, int], dict[str, Any]]
    dead_notes: dict[int, dict[str, Any]]
    torn_lines: dict[str, int]
    sources: list[str]

    @property
    def generations(self) -> list[int]:
        gens = set(self.worlds)
        gens.update(
            int(e["generation"])
            for e in self.events
            if e.get("event") == "generation_start"
            and isinstance(e.get("generation"), int)
        )
        gens.update(
            int(s["generation"])
            for s in self.stamps
            if isinstance(s.get("generation"), int)
        )
        return sorted(gens)

    @property
    def ranks(self) -> list[int]:
        out: set[int] = set()
        for world in self.worlds.values():
            out.update(int(r) for r in world.get("ranks", ()))
        out.update(
            int(s["global_rank"])
            for s in self.stamps
            if isinstance(s.get("global_rank"), int)
        )
        out.update(rank for _, rank in self.heartbeats)
        return sorted(out)


def _read_jsonl_tolerant(path: str) -> tuple[list[dict[str, Any]], int]:
    """Parse every intact line; count torn ones (a writer SIGKILLed
    mid-record leaves at most one, at the tail)."""
    records: list[dict[str, Any]] = []
    torn = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    torn += 1
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass
    return records, torn


def _num(rec: Mapping[str, Any], key: str) -> float | None:
    val = rec.get(key)
    return float(val) if isinstance(val, (int, float)) else None


def _stamp_from_step_record(rec: Mapping[str, Any]) -> dict[str, Any] | None:
    """Engine ``kind:"step"`` records carry the same sync stamps when
    telemetry was due — adapt them so multi-process training runs with
    per-rank ``--metrics-dir`` streams feed skew attribution without a
    dedicated stamper."""
    if _num(rec, "sync_enter_wall") is None:
        return None
    out: dict[str, Any] = {
        "kind": "fleet_stamp",
        "source": "step_record",
        "step": rec.get("step"),
        "generation": int(rec.get("generation", 0)),
        "global_rank": int(rec.get("global_rank", rec.get("process_id", 0))),
    }
    for key in (
        "sync_enter_wall",
        "sync_enter_mono",
        "sync_exit_wall",
        "sync_exit_mono",
        "step_enter_wall",
        "step_enter_mono",
        "step_exit_wall",
        "step_exit_mono",
    ):
        if _num(rec, key) is not None:
            out[key] = float(rec[key])
    return out


def _scan_store_json(
    root: str,
) -> tuple[
    dict[int, dict[str, Any]],
    dict[int, dict[int, dict[str, Any]]],
    dict[tuple[int, int], dict[str, Any]],
    dict[int, dict[str, Any]],
]:
    worlds: dict[int, dict[str, Any]] = {}
    barriers: dict[int, dict[int, dict[str, Any]]] = {}
    heartbeats: dict[tuple[int, int], dict[str, Any]] = {}
    dead_notes: dict[int, dict[str, Any]] = {}
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if not name.endswith(".json") or not os.path.isfile(path):
            continue
        kind = None
        for prefix in ("world_g", "sync_g", "hb_g", "dead_g"):
            if name.startswith(prefix):
                kind = prefix
                break
        if kind is None:
            continue
        stem = name[len(kind):-len(".json")]
        try:
            if "_r" in stem:
                gen_s, rank_s = stem.split("_r", 1)
                gen, rank = int(gen_s), int(rank_s)
            else:
                gen, rank = int(stem), None
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError, json.JSONDecodeError):
            continue
        if kind == "world_g":
            worlds[gen] = rec
        elif kind == "sync_g" and rank is not None:
            barriers.setdefault(gen, {})[rank] = rec
        elif kind == "hb_g" and rank is not None:
            heartbeats[(gen, rank)] = rec
        elif kind == "dead_g":
            dead_notes[gen] = rec
    return worlds, barriers, heartbeats, dead_notes


def load_fleet_dir(root: str) -> FleetData:
    """Ingest a rendezvous-store directory (or any run dir that follows
    its layout): world/heartbeat/death-note/barrier files, the
    ``events.jsonl`` stream, per-rank ``fleet/`` stamp streams, and any
    other ``*.jsonl`` telemetry found below the root (per-rank metrics
    dirs, flight-recorder dumps) — classified per record, never per
    file."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        raise FileNotFoundError(f"{root}: not a directory")
    worlds, barriers, heartbeats, dead_notes = _scan_store_json(root)
    events: list[dict[str, Any]] = []
    stamps: list[dict[str, Any]] = []
    torn_lines: dict[str, int] = {}
    sources: list[str] = []

    jsonl_paths: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "logs"]
        for name in sorted(filenames):
            if name.endswith(".jsonl"):
                jsonl_paths.append(os.path.join(dirpath, name))

    for path in jsonl_paths:
        records, torn = _read_jsonl_tolerant(path)
        rel = os.path.relpath(path, root)
        if torn:
            torn_lines[rel] = torn
        used = False
        for rec in records:
            kind = rec.get("kind")
            if kind == "fleet_stamp":
                stamps.append(rec)
                used = True
            elif kind == "event":
                events.append(rec)
                used = True
            elif kind == "step":
                adapted = _stamp_from_step_record(rec)
                if adapted is not None:
                    stamps.append(adapted)
                    used = True
        if used or torn:
            sources.append(rel)

    events.sort(key=lambda e: _num(e, "time") or 0.0)
    stamps.sort(
        key=lambda s: (
            s.get("generation", 0) or 0,
            s.get("step", 0) or 0,
            s.get("global_rank", 0) or 0,
        )
    )
    return FleetData(
        root=root,
        worlds=worlds,
        events=events,
        stamps=stamps,
        barrier_stamps=barriers,
        heartbeats=heartbeats,
        dead_notes=dead_notes,
        torn_lines=torn_lines,
        sources=sources,
    )


# ------------------------------------------------------ clock alignment
class ClockAligner:
    """Map every rank's stamps onto one shared timeline using the
    rendezvous-barrier anchors (see module docstring for the model).
    The reference is the lowest-ranked anchor of each generation; a
    (generation, rank) without an anchor passes wall time through
    uncorrected and is counted in ``unanchored``."""

    def __init__(
        self, barrier_stamps: Mapping[int, Mapping[int, Mapping[str, Any]]]
    ):
        self._anchors: dict[tuple[int, int], dict[str, float]] = {}
        self._refs: dict[int, int] = {}
        for gen, per_rank in barrier_stamps.items():
            usable = {
                int(rank): rec
                for rank, rec in per_rank.items()
                if _num(rec, "wall") is not None
            }
            if not usable:
                continue
            self._refs[int(gen)] = min(usable)
            for rank, rec in usable.items():
                anchor = {"wall": float(rec["wall"])}
                mono = _num(rec, "mono")
                if mono is not None:
                    anchor["mono"] = mono
                self._anchors[(int(gen), rank)] = anchor
        self.unanchored: set[tuple[int, int]] = set()

    def reference_rank(self, generation: int) -> int | None:
        return self._refs.get(int(generation))

    def wall_offset(self, generation: int, rank: int) -> float | None:
        """Rank's barrier wall minus the reference's — the correction
        subtracted from the rank's wall stamps (0.0 for the reference,
        sub-millisecond between synced clocks on one machine)."""
        ref = self._refs.get(int(generation))
        if ref is None:
            return None
        anchor = self._anchors.get((int(generation), int(rank)))
        ref_anchor = self._anchors[(int(generation), ref)]
        if anchor is None:
            return None
        return anchor["wall"] - ref_anchor["wall"]

    def aligned(
        self,
        generation: int,
        rank: int,
        *,
        wall: float | None = None,
        mono: float | None = None,
    ) -> float | None:
        """A (wall, mono) stamp from ``rank`` in ``generation`` on the
        reference timeline; None only when no time is recoverable."""
        gen, rank = int(generation), int(rank)
        ref = self._refs.get(gen)
        anchor = self._anchors.get((gen, rank))
        if ref is not None and anchor is not None:
            ref_anchor = self._anchors[(gen, ref)]
            if (
                mono is not None
                and "mono" in anchor
                and "mono" in ref_anchor
            ):
                return ref_anchor["wall"] + (mono - anchor["mono"])
            if wall is not None:
                return wall - (anchor["wall"] - ref_anchor["wall"])
        if wall is not None:
            self.unanchored.add((gen, rank))
            return wall
        return None

    def aligned_record(
        self,
        rec: Mapping[str, Any],
        wall_key: str,
        mono_key: str,
    ) -> float | None:
        return self.aligned(
            int(rec.get("generation", 0)),
            int(rec.get("global_rank", 0)),
            wall=_num(rec, wall_key),
            mono=_num(rec, mono_key),
        )


# ------------------------------------------------------ skew attribution
def collective_skew(
    data: FleetData, aligner: ClockAligner | None = None
) -> list[dict[str, Any]]:
    """Per-(generation, step) collective arrival analysis.

    For every step at least two ranks completed, align each rank's
    ``sync_enter`` stamp (its arrival at the blocking fetch), name the
    straggler (latest arrival), and charge every earlier rank the wait
    it spent inside the collective: ``collective_wait_ms[r] =
    latest_arrival - arrival[r]``. The first stamped step of each
    generation is flagged ``warmup`` (it pays compilation, so its
    spread is noise, not a straggler signal); ``full_coverage`` says
    every rank of the generation's world reported."""
    aligner = ClockAligner(data.barrier_stamps) if aligner is None else aligner
    groups: dict[tuple[int, int], dict[int, dict[str, Any]]] = {}
    for rec in data.stamps:
        step = rec.get("step")
        gen = rec.get("generation")
        rank = rec.get("global_rank")
        if (
            not isinstance(step, int)
            or not isinstance(gen, int)
            or not isinstance(rank, int)
            or _num(rec, "sync_enter_wall") is None
            and _num(rec, "sync_enter_mono") is None
        ):
            continue
        groups.setdefault((gen, step), {})[rank] = rec

    first_step: dict[int, int] = {}
    for gen, step in groups:
        if gen not in first_step or step < first_step[gen]:
            first_step[gen] = step

    rows: list[dict[str, Any]] = []
    for (gen, step), per_rank in sorted(groups.items()):
        if len(per_rank) < 2:
            continue
        arrivals: dict[int, float] = {}
        for rank, rec in per_rank.items():
            t = aligner.aligned_record(rec, "sync_enter_wall", "sync_enter_mono")
            if t is not None:
                arrivals[rank] = t
        if len(arrivals) < 2:
            continue
        latest = max(arrivals.values())
        straggler = max(arrivals, key=lambda r: (arrivals[r], r))
        world = data.worlds.get(gen, {})
        world_ranks = {int(r) for r in world.get("ranks", ())}
        rows.append(
            {
                "kind": "fleet_skew",
                "generation": gen,
                "step": step,
                "ranks": sorted(arrivals),
                "straggler": straggler,
                "skew_ms": (latest - min(arrivals.values())) * 1e3,
                "collective_wait_ms": {
                    str(r): (latest - t) * 1e3
                    for r, t in sorted(arrivals.items())
                },
                "warmup": step == first_step.get(gen),
                "full_coverage": bool(world_ranks)
                and set(arrivals) == world_ranks,
            }
        )
    return rows


# ------------------------------------------------------ merged timeline
def _event_rank(event: Mapping[str, Any]) -> int | None:
    """Which rank's lane an event instant belongs on: explicit victim /
    exiter fields first, then the writer's own runtime label — except
    for supervisor-authored events, whose labels describe the
    supervisor, not a worker."""
    for key in ("dead_rank", "exit_rank"):
        if isinstance(event.get(key), int):
            return int(event[key])
    if event.get("event") in SUPERVISOR_EVENTS:
        return None
    rank = event.get("global_rank")
    return int(rank) if isinstance(rank, int) else None


def _event_name(event: Mapping[str, Any]) -> str:
    name = str(event.get("event", "event"))
    if name == "worker_death":
        reason = event.get("reason")
        if reason in ("heartbeat_stale", "never_heartbeat"):
            return f"missed heartbeat r{event.get('dead_rank')}"
        return f"death r{event.get('dead_rank')} ({reason})"
    if name == "reelection":
        return (
            f"re-election g{event.get('parent_generation')}"
            f"->g{event.get('generation')}"
        )
    if name == "generation_start":
        gen = event.get("generation")
        return f"re-exec g{gen}" if gen else f"start g{gen}"
    if name == "chaos_inject":
        return f"chaos {event.get('fault', '?')}"
    return name.replace("_", " ")


def merge_timeline(
    data: FleetData,
    aligner: ClockAligner | None = None,
    skew: Iterable[Mapping[str, Any]] | None = None,
) -> dict[str, Any]:
    """One Chrome/Perfetto trace for the whole run: pid 0 is the fleet
    lane (generation track + supervisor instants), pid r+1 is global
    rank r (stable across generations — a survivor's lane continues
    into g+1). Per rank, tid 0 carries step spans and tid 1 the
    collective window (sync-enter → sync-exit), annotated with the
    attributed wait when ``skew`` rows are supplied."""
    aligner = ClockAligner(data.barrier_stamps) if aligner is None else aligner
    wait_by_step: dict[tuple[int, int], Mapping[str, Any]] = {
        (int(row["generation"]), int(row["step"])): row
        for row in (skew or ())
    }

    # Pass 1: aligned times for every drawable item, to fix t0.
    drawables: list[tuple[float, str, Any]] = []  # (t, kind, payload)
    for event in data.events:
        gen = event.get("generation")
        rank = _event_rank(event)
        t = None
        if event.get("event") not in SUPERVISOR_EVENTS and rank is not None:
            t = aligner.aligned(
                int(gen) if isinstance(gen, int) else 0,
                rank,
                wall=_num(event, "time"),
                mono=_num(event, "monotonic"),
            )
        if t is None:
            t = _num(event, "time")
        if t is not None:
            drawables.append((t, "event", event))
    for gen, note in data.dead_notes.items():
        t = _num(note, "time")
        if t is not None:
            drawables.append((t, "dead_note", (gen, note)))
    spans: list[tuple[int, int, int, float, float, float | None]] = []
    # (rank, gen, step, step_enter, step_exit, sync bounds via lookup)
    stamp_times: list[tuple[float, float, dict[str, Any]]] = []
    for rec in data.stamps:
        rank = rec.get("global_rank")
        if not isinstance(rank, int):
            continue
        enter = aligner.aligned_record(rec, "step_enter_wall", "step_enter_mono")
        exit_ = aligner.aligned_record(rec, "step_exit_wall", "step_exit_mono")
        s_in = aligner.aligned_record(rec, "sync_enter_wall", "sync_enter_mono")
        s_out = aligner.aligned_record(rec, "sync_exit_wall", "sync_exit_mono")
        if enter is None:
            enter = s_in
        if exit_ is None:
            exit_ = s_out
        if enter is None or exit_ is None:
            continue
        stamp_times.append((enter, exit_, rec))
        drawables.append((enter, "stamp", (rec, enter, exit_, s_in, s_out)))

    if not drawables:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(t for t, _, _ in drawables)

    def us(t: float) -> int:
        return int(round((t - t0) * 1e6))

    trace: list[dict[str, Any]] = []
    ranks = data.ranks

    # Process/thread metadata: fleet lane first, then one pid per rank.
    trace.append(
        {
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "name": "process_name",
            "args": {"name": "fleet"},
        }
    )
    trace.append(
        {
            "ph": "M",
            "pid": 0,
            "tid": 0,
            "name": "process_sort_index",
            "args": {"sort_index": -1},
        }
    )
    for rank in ranks:
        pid = rank + 1
        trace.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "process_name",
                "args": {"name": f"rank {rank}"},
            }
        )
        trace.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "thread_name",
                "args": {"name": "steps"},
            }
        )
        trace.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": 1,
                "name": "thread_name",
                "args": {"name": "collective"},
            }
        )

    # Generation track: one span per generation on the fleet lane, from
    # its generation_start to the next one (or the last drawable).
    t_end = max(
        max((t for t, _, _ in drawables)),
        max((e for _, e, _ in stamp_times), default=t0),
    )
    gen_starts: dict[int, float] = {}
    for t, kind, payload in drawables:
        if kind == "event" and payload.get("event") == "generation_start":
            gen = payload.get("generation")
            if isinstance(gen, int) and gen not in gen_starts:
                gen_starts[gen] = t
    for gen, start in sorted(gen_starts.items()):
        seal = gen_starts.get(gen + 1, t_end)
        trace.append(
            {
                "ph": "X",
                "pid": 0,
                "tid": 0,
                "name": f"generation {gen}",
                "cat": "generation",
                "ts": us(start),
                "dur": max(us(seal) - us(start), 1),
                "args": {"generation": gen},
            }
        )

    # Step + collective spans per rank.
    for t, kind, payload in drawables:
        if kind != "stamp":
            continue
        rec, enter, exit_, s_in, s_out = payload
        rank = int(rec["global_rank"])
        gen = int(rec.get("generation", 0))
        step = rec.get("step")
        args: dict[str, Any] = {"step": step, "generation": gen}
        trace.append(
            {
                "ph": "X",
                "pid": rank + 1,
                "tid": 0,
                "name": f"step {step}",
                "cat": "step",
                "ts": us(enter),
                "dur": max(us(exit_) - us(enter), 1),
                "args": args,
            }
        )
        if s_in is not None and s_out is not None:
            c_args = dict(args)
            row = wait_by_step.get((gen, step)) if isinstance(step, int) else None
            if row is not None:
                wait = row.get("collective_wait_ms", {}).get(str(rank))
                if wait is not None:
                    c_args["collective_wait_ms"] = round(float(wait), 3)
                c_args["straggler"] = row.get("straggler")
            trace.append(
                {
                    "ph": "X",
                    "pid": rank + 1,
                    "tid": 1,
                    "name": "collective",
                    "cat": "collective",
                    "ts": us(s_in),
                    "dur": max(us(s_out) - us(s_in), 1),
                    "args": c_args,
                }
            )

    # Instant markers.
    for t, kind, payload in drawables:
        if kind == "event":
            event = payload
            rank = _event_rank(event)
            trace.append(
                {
                    "ph": "i",
                    "pid": 0 if rank is None else rank + 1,
                    "tid": 0,
                    "name": _event_name(event),
                    "cat": "incident",
                    "ts": us(t),
                    "s": "g" if rank is None else "p",
                    "args": {
                        k: v
                        for k, v in event.items()
                        if k not in ("kind", "time", "monotonic")
                        and isinstance(v, (str, int, float, bool, list))
                    },
                }
            )
        elif kind == "dead_note":
            gen, note = payload
            trace.append(
                {
                    "ph": "i",
                    "pid": 0,
                    "tid": 0,
                    "name": f"death note g{gen} {note.get('dead')}",
                    "cat": "incident",
                    "ts": us(t),
                    "s": "g",
                    "args": {"generation": gen, "dead": note.get("dead")},
                }
            )

    trace.sort(key=lambda e: (e.get("ts", 0), e["pid"], e["tid"]))
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------- audit
def fleet_check(
    data: FleetData,
    aligner: ClockAligner | None = None,
    *,
    tolerance_s: float = 0.75,
) -> list[str]:
    """Incident-consistency audit over one run directory. Returns
    human-readable problems (empty = consistent):

    - every generation g>0 has a parent world AND a re-election event
      naming it (no orphan generations);
    - every death note / worker_death pairs with a re-election out of
      that generation and a re-exec (``generation_start``) into g+1
      whose world is exactly the survivors — unless the supervisor
      recorded ``recovery_giveup``;
    - kill → death → re-election → re-exec appear in causal order on
      the aligned timeline (within ``tolerance_s``);
    - no completed step span crosses its generation's seal (the next
      generation's start) — a span that straddles the seal means a rank
      kept stepping in a world that no longer existed;
    - stamps are internally ordered (enter ≤ sync-enter ≤ sync-exit ≤
      exit).
    """
    aligner = ClockAligner(data.barrier_stamps) if aligner is None else aligner
    problems: list[str] = []
    events = data.events
    worlds = data.worlds

    def evs(name: str, **match: Any) -> list[dict[str, Any]]:
        out = []
        for e in events:
            if e.get("event") != name:
                continue
            if all(e.get(k) == v for k, v in match.items()):
                out.append(e)
        return out

    # -- orphan generations
    for gen in sorted(worlds):
        if gen == 0:
            continue
        if gen - 1 not in worlds:
            problems.append(
                f"orphan generation {gen}: no world spec for parent "
                f"generation {gen - 1}"
            )
        if not evs("reelection", generation=gen):
            problems.append(
                f"orphan generation {gen}: no re-election event elected it"
            )

    # -- deaths pair with re-election + re-exec into g+1
    deaths_by_gen: dict[int, set[int]] = {}
    for e in evs("worker_death"):
        gen = e.get("generation")
        rank = e.get("dead_rank")
        if isinstance(gen, int) and isinstance(rank, int):
            deaths_by_gen.setdefault(gen, set()).add(rank)
    for gen, note in data.dead_notes.items():
        deaths_by_gen.setdefault(int(gen), set()).update(
            int(r) for r in note.get("dead", ())
        )

    for gen, dead in sorted(deaths_by_gen.items()):
        if evs("recovery_giveup", generation=gen):
            continue
        reelections = evs("reelection", parent_generation=gen)
        if not reelections:
            problems.append(
                f"death of rank(s) {sorted(dead)} in generation {gen} has "
                f"no re-election out of it (and no giveup)"
            )
            continue
        child = gen + 1
        if not evs("generation_start", generation=child):
            problems.append(
                f"re-election g{gen}->g{child} was never re-exec'd "
                f"(no generation_start for {child})"
            )
        child_world = worlds.get(child)
        parent_world = worlds.get(gen)
        if child_world is None:
            problems.append(
                f"re-election g{gen}->g{child} left no world spec for "
                f"generation {child}"
            )
        elif parent_world is not None:
            survivors = {
                int(r) for r in parent_world.get("ranks", ())
            } - dead
            child_ranks = {int(r) for r in child_world.get("ranks", ())}
            if child_ranks != survivors:
                problems.append(
                    f"generation {child} world {sorted(child_ranks)} != "
                    f"survivors {sorted(survivors)} of generation {gen}"
                )

    # -- causal order on the aligned timeline
    def ev_time(e: Mapping[str, Any]) -> float | None:
        rank = _event_rank(e)
        if e.get("event") not in SUPERVISOR_EVENTS and rank is not None:
            gen = e.get("generation")
            t = aligner.aligned(
                int(gen) if isinstance(gen, int) else 0,
                rank,
                wall=_num(e, "time"),
                mono=_num(e, "monotonic"),
            )
            if t is not None:
                return t
        return _num(e, "time")

    for gen, dead in sorted(deaths_by_gen.items()):
        chain: list[tuple[str, float]] = []
        kills = [
            e
            for e in evs("chaos_inject", generation=gen)
            if e.get("fault") == "process_kill"
        ]
        kill_times = [t for t in (ev_time(e) for e in kills) if t is not None]
        if kill_times:
            chain.append(("chaos kill", min(kill_times)))
        death_times = [
            t
            for t in (ev_time(e) for e in evs("worker_death", generation=gen))
            if t is not None
        ]
        if death_times:
            chain.append(("death", min(death_times)))
        note = data.dead_notes.get(gen)
        if note is not None and _num(note, "time") is not None:
            chain.append(("death note", float(note["time"])))
        for e in evs("reelection", parent_generation=gen):
            t = ev_time(e)
            if t is not None:
                chain.append(("re-election", t))
        for e in evs("generation_start", generation=gen + 1):
            t = ev_time(e)
            if t is not None:
                chain.append(("re-exec", t))
        for (name_a, t_a), (name_b, t_b) in zip(chain, chain[1:]):
            if t_b < t_a - tolerance_s:
                problems.append(
                    f"generation {gen}: {name_b} at {t_b:.3f} precedes "
                    f"{name_a} at {t_a:.3f} (aligned) — causality violated"
                )

    # -- seals and stamp sanity
    gen_start_times: dict[int, float] = {}
    for e in evs("generation_start"):
        gen = e.get("generation")
        t = ev_time(e)
        if isinstance(gen, int) and t is not None and gen not in gen_start_times:
            gen_start_times[gen] = t
    for rec in data.stamps:
        gen = rec.get("generation")
        rank = rec.get("global_rank")
        step = rec.get("step")
        if not isinstance(gen, int) or not isinstance(rank, int):
            continue
        order = [
            _num(rec, f"{k}_mono")
            for k in ("step_enter", "sync_enter", "sync_exit", "step_exit")
        ]
        present = [t for t in order if t is not None]
        if present != sorted(present):
            problems.append(
                f"stamp g{gen} r{rank} step {step}: timestamps out of "
                f"order {present}"
            )
        seal = gen_start_times.get(gen + 1)
        if seal is None:
            continue
        exit_t = aligner.aligned_record(rec, "step_exit_wall", "step_exit_mono")
        if exit_t is not None and exit_t > seal + tolerance_s:
            problems.append(
                f"stamp g{gen} r{rank} step {step}: step exit at "
                f"{exit_t:.3f} crosses the generation seal at {seal:.3f}"
            )

    return problems


# --------------------------------------------------------------- report
def fleet_report_records(
    data: FleetData,
    skew: list[dict[str, Any]],
    problems: list[str],
) -> list[dict[str, Any]]:
    """Flat records for ``benchmarks/metrics_summary.py``: the skew rows
    plus one ``fleet_incident`` per lifecycle event and a summary."""
    records: list[dict[str, Any]] = []
    incident_names = (
        "chaos_inject",
        "worker_death",
        "reelection",
        "generation_start",
        "recovery_giveup",
        "process_loss",
        "run_complete",
    )
    for e in data.events:
        if e.get("event") in incident_names:
            records.append(
                {
                    "kind": "fleet_incident",
                    "event": e.get("event"),
                    "generation": e.get("generation"),
                    "time": e.get("time"),
                    "rank": _event_rank(e),
                }
            )
    records.extend(skew)
    post = [r for r in skew if not r["warmup"]]
    records.append(
        {
            "kind": "fleet_summary",
            "generations": data.generations,
            "ranks": data.ranks,
            "steps_attributed": len(skew),
            "max_skew_ms": max((r["skew_ms"] for r in post), default=None),
            "problems": len(problems),
            "torn_lines": sum(data.torn_lines.values()),
        }
    )
    return records


def render_fleet_report(
    data: FleetData,
    skew: list[dict[str, Any]],
    problems: list[str],
    aligner: ClockAligner | None = None,
) -> str:
    aligner = ClockAligner(data.barrier_stamps) if aligner is None else aligner
    lines = [f"graftfleet report — {data.root}"]
    for gen in data.generations:
        world = data.worlds.get(gen, {})
        ranks = [int(r) for r in world.get("ranks", ())]
        dead = sorted(data.dead_notes.get(gen, {}).get("dead", ()))
        ref = aligner.reference_rank(gen)
        parts = [f"g{gen}: ranks {ranks or '?'}"]
        if world.get("coordinator_rank") is not None:
            parts.append(f"coordinator r{world['coordinator_rank']}")
        if ref is not None:
            offsets = [
                f"r{r}{(aligner.wall_offset(gen, r) or 0) * 1e3:+.1f}ms"
                for r in ranks
                if aligner.wall_offset(gen, r) is not None and r != ref
            ]
            parts.append(
                f"clock ref r{ref}" + (f" ({' '.join(offsets)})" if offsets else "")
            )
        if dead:
            parts.append(f"dead {dead}")
        lines.append("  " + " | ".join(parts))

    incidents = [
        e
        for e in data.events
        if e.get("event")
        in (
            "chaos_inject",
            "worker_death",
            "reelection",
            "generation_start",
            "recovery_giveup",
            "process_loss",
            "run_complete",
        )
    ]
    if incidents:
        t0 = min(_num(e, "time") or 0.0 for e in incidents)
        lines.append(f"  incidents ({len(incidents)}):")
        for e in incidents:
            t = (_num(e, "time") or 0.0) - t0
            lines.append(f"    +{t:7.3f}s  {_event_name(e)}")

    post = [r for r in skew if not r["warmup"]]
    if skew:
        named: dict[int, int] = {}
        for row in post:
            named[row["straggler"]] = named.get(row["straggler"], 0) + 1
        top = sorted(named.items(), key=lambda kv: -kv[1])
        lines.append(
            f"  collective skew: {len(skew)} steps attributed "
            f"({len(post)} post-warmup)"
        )
        if post:
            skews = sorted(r["skew_ms"] for r in post)
            lines.append(
                f"    skew_ms median {skews[len(skews) // 2]:.1f} "
                f"max {skews[-1]:.1f}"
            )
        if top:
            lines.append(
                "    stragglers: "
                + ", ".join(f"r{r} x{n}" for r, n in top)
            )
    else:
        lines.append("  collective skew: no stamped steps found")

    if data.torn_lines:
        for rel, n in sorted(data.torn_lines.items()):
            lines.append(f"  torn lines: {n} in {rel}")
    if problems:
        lines.append(f"  audit: {len(problems)} problem(s)")
        for prob in problems:
            lines.append(f"    !! {prob}")
    else:
        lines.append("  audit: OK")
    return "\n".join(lines)


def write_fleet_artifacts(
    root: str, out_dir: str | None = None
) -> dict[str, Any]:
    """Load a run dir and leave ``fleet_trace.json`` (Perfetto) +
    ``fleet_report.json`` next to it. Returns paths, problems, and the
    rendered text report — the supervisor logs the text and CI gates on
    the problems."""
    data = load_fleet_dir(root)
    aligner = ClockAligner(data.barrier_stamps)
    skew = collective_skew(data, aligner)
    problems = fleet_check(data, aligner)
    trace = merge_timeline(data, aligner, skew)
    out_dir = data.root if out_dir is None else os.path.abspath(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, TRACE_NAME)
    with open(trace_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    report_path = os.path.join(out_dir, REPORT_NAME)
    report = {
        "kind": "fleet_report",
        "root": data.root,
        "generations": data.generations,
        "ranks": data.ranks,
        "problems": problems,
        "records": fleet_report_records(data, skew, problems),
    }
    with open(report_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    return {
        "trace": trace_path,
        "report": report_path,
        "problems": problems,
        "text": render_fleet_report(data, skew, problems, aligner),
    }
