"""Analytic FLOPs models and MFU accounting.

Moved here from ``bench.py`` (which re-exports for backward compat) so
training telemetry and the benchmark share ONE definition of model
FLOPs and peak throughput.

Conventions (the standard MFU accounting):
- FLOPs = 2 * MACs.
- Training = 3x forward (backward is dgrad + wgrad, each ~1x forward);
  for transformers this is the familiar 6*N*D rule — 3x on 2*N*D.
- Bandwidth-bound ops (BN, activations, pooling, data augmentation)
  are excluded.
- MFU = achieved model FLOPs/sec divided by *peak dense* FLOPs of the
  chip — not "hardware FLOPs" including recompute, so MFU is
  comparable across implementations.
"""

from __future__ import annotations

__all__ = [
    "V5E_PEAK_FLOPS",
    "peak_flops_per_chip",
    "peak_hbm_bytes_per_sec",
    "resnet18_cifar_train_flops_per_sample",
    "transformer_train_flops_per_token",
    "mfu",
]

# TPU v5e (v5 lite) peak dense bf16 throughput, per chip.
V5E_PEAK_FLOPS = 197e12

# Peak dense bf16 FLOPs/sec per chip by jax device_kind substring.
# Only kinds we can vouch for; unknown kinds (and CPU) map to None so
# an MFU figure is never fabricated against a made-up peak.
_PEAKS: tuple[tuple[str, float], ...] = (
    ("v5 lite", V5E_PEAK_FLOPS),
    ("v5e", V5E_PEAK_FLOPS),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
)


# Peak HBM bandwidth (bytes/sec) per chip, same matching discipline.
# Pairs with _PEAKS to give each chip's roofline ridge point
# (peak_flops / peak_hbm_bw) for graftscope's phase classification.
_HBM_PEAKS: tuple[tuple[str, float], ...] = (
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v4", 1228e9),
    ("v6 lite", 1638e9),
    ("v6e", 1638e9),
)


def peak_flops_per_chip(device_kind: str) -> float | None:
    """Peak dense bf16 FLOPs/sec for a jax ``device_kind`` string, or
    None when the kind is unknown (CPU, GPU, future TPUs) — callers
    must then report MFU as null rather than guess."""
    kind = device_kind.lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak
    return None


def peak_hbm_bytes_per_sec(device_kind: str) -> float | None:
    """Peak HBM bandwidth (bytes/sec) for a jax ``device_kind``, or
    None when unknown — roofline classifiers then fall back to a
    documented default ridge instead of a fabricated one."""
    kind = device_kind.lower()
    for sub, peak in _HBM_PEAKS:
        if sub in kind:
            return peak
    return None


def resnet18_cifar_train_flops_per_sample() -> float:
    """Analytic model FLOPs of one ResNet-18/CIFAR training step, per
    sample. Counts convs, the stage-entry 1x1 projections, and the FC
    head (``models/resnet.py`` cifar_stem architecture: 3x3 stem at
    32x32, stages (2,2,2,2) at 64/128/256/512 ch, strides 1/2/2/2)."""

    def conv(hw: int, cin: int, cout: int, k: int = 3) -> float:
        return 2.0 * hw * hw * cin * cout * k * k  # per output position

    f = conv(32, 3, 64)  # stem
    cin = 64
    for cout, hw in ((64, 32), (128, 16), (256, 8), (512, 4)):
        f += conv(hw, cin, cout) + conv(hw, cout, cout)  # block 0
        if cin != cout:  # stage-entry projection shortcut
            f += conv(hw, cin, cout, k=1)
        f += 2 * conv(hw, cout, cout)  # block 1
        cin = cout
    f += 2.0 * 512 * 10  # FC head
    return 3.0 * f


def transformer_train_flops_per_token(n_params: int | float) -> float:
    """The 6*N rule: ~6 FLOPs per parameter per trained token (2N
    forward, 4N backward). Attention-score FLOPs are excluded, as in
    the PaLM/Chinchilla MFU convention for seq_len << d_model regimes;
    for this repo's short-sequence LMs the correction is <2%."""
    return 6.0 * float(n_params)


def mfu(
    achieved_flops_per_sec_per_chip: float, device_kind: str
) -> float | None:
    """Model FLOPs utilization in [0, 1], or None off known TPUs."""
    peak = peak_flops_per_chip(device_kind)
    if peak is None:
        return None
    return achieved_flops_per_sec_per_chip / peak
