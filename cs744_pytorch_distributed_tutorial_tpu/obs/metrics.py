"""On-device metric computation + the host-side ``Telemetry`` front-end.

Two halves, deliberately in one module so the contract between them is
visible in one place:

**In-graph helpers** (traced inside the jitted train step) — tree
norms, MoE expert-load entropy, sown-metric collection. These compute
scalars *on device*; the host only ever sees them at the existing
loss-logging fetch, so telemetry adds zero extra device↔host
round-trips and never breaks async dispatch.

**Host side** — :class:`Telemetry` owns the sinks (an in-memory ring
always, for the watchdog; a rank-0-gated JSONL when ``metrics_dir`` is
set), stamps records with run/kind/time, derives amortized
``step_time_s`` between emissions, and computes MFU when the engine
declared its analytic FLOPs per step.

Record schema (all records are flat JSON objects):

- ``kind="step"``: ``run, step, time, loss, grad_norm, param_norm,
  lr, grad_sync_bytes, step_time_s, mfu, ...`` (engine-specific
  extras such as ``moe_aux`` ride along). ``grad_sync_bytes`` is
  audited: graftcheck's TA003 recomputes bytes-on-wire from the traced
  step's collective eqns and fails CI if the analytic accounting
  drifts more than 1% from the trace (``analysis/trace/``).
- ``kind="system"``: HBM + compile counters (see ``obs/system.py``).
- ``kind="event"``: one-off markers — watchdog firings, divergence
  verdicts, eval results, speculative-decode stats.
"""

from __future__ import annotations

import os
import time
from typing import Any, Iterable, Mapping

from .sinks import JsonlSink, MetricSink, MultiSink, RingSink, rank_zero
from . import flops as _flops
from . import run_manifest as _run_manifest
from . import system as _system

__all__ = [
    "tree_l2_norm",
    "tree_sq_norm",
    "expert_load_entropy",
    "speculative_accept_rate",
    "sown_scalar_mean",
    "Telemetry",
]

METRICS_NAME = "metrics.jsonl"


# ---------------------------------------------------------------------------
# In-graph helpers (trace-time; must stay jit-friendly)
# ---------------------------------------------------------------------------


def tree_sq_norm(tree: Any, specs: Any = None) -> Any:
    """Sum of squares over a pytree, in f32, as a 0-d array.

    With ``specs`` (a matching pytree of ``PartitionSpec``), each leaf
    that is *sharded* inside the enclosing ``shard_map`` is psummed
    over exactly the mesh axes its spec names, so the result is the
    GLOBAL sum of squares and is identical on every device. Replicated
    leaves (empty spec) are counted once — no double counting.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def leaf_sq(x: Any) -> Any:
        return jnp.sum(jnp.square(x.astype(jnp.float32)))

    leaves = jax.tree_util.tree_leaves(tree)
    if specs is None:
        total = sum((leaf_sq(x) for x in leaves), jnp.float32(0.0))
        return total

    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: s is None or hasattr(s, "index")
    )
    total = jnp.float32(0.0)
    for x, spec in zip(leaves, spec_leaves):
        sq = leaf_sq(x)
        axes: list[str] = []
        for entry in tuple(spec or ()):
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                axes.extend(str(a) for a in entry)
            else:
                axes.append(str(entry))
        if axes:
            sq = lax.psum(sq, tuple(dict.fromkeys(axes)))
        total = total + sq
    return total


def tree_l2_norm(tree: Any, specs: Any = None) -> Any:
    """Global L2 norm of a pytree (see :func:`tree_sq_norm`)."""
    import jax.numpy as jnp

    return jnp.sqrt(tree_sq_norm(tree, specs))


def expert_load_entropy(load: Any) -> Any:
    """Normalized entropy of per-expert token-load fractions.

    ``load`` is the router's per-expert fraction of tokens (sums to 1
    over experts). Returns entropy / log(E) in [0, 1]: 1.0 means
    perfectly balanced routing, 0.0 means total collapse onto one
    expert. The normalization makes runs with different expert counts
    comparable on one chart.
    """
    import jax.numpy as jnp

    load = load.astype(jnp.float32)
    e = load.shape[-1]
    if e <= 1:
        return jnp.float32(1.0)
    p = load / jnp.maximum(jnp.sum(load, axis=-1, keepdims=True), 1e-9)
    ent = -jnp.sum(p * jnp.log(p + 1e-9), axis=-1)
    return jnp.mean(ent) / jnp.log(jnp.float32(e))


def sown_scalar_mean(collection: Any, name: str) -> Any:
    """Mean of every value sown under key ``name`` anywhere inside a
    nested flax collection dict (flax stores sows as tuples).

    Returns an f32 0-d array; 0.0 when nothing was sown — so callers
    can keep their metrics dict static across module configurations.
    """
    import jax.numpy as jnp

    vals: list[Any] = []

    def walk(node: Any) -> None:
        if isinstance(node, Mapping):
            for k, v in node.items():
                if k == name:
                    for item in v if isinstance(v, (tuple, list)) else (v,):
                        vals.append(jnp.mean(item.astype(jnp.float32)))
                else:
                    walk(v)

    walk(collection)
    if not vals:
        return jnp.float32(0.0)
    return sum(vals[1:], vals[0]) / len(vals)


# ---------------------------------------------------------------------------
# Host-side helpers
# ---------------------------------------------------------------------------


def speculative_accept_rate(
    new_tokens: int, target_calls: int, k: int
) -> float | None:
    """Realized draft-acceptance rate of a speculative-decode run.

    Each target call scores one draft block and always yields >=1
    token; accepted drafts yield the rest. With ``k`` drafted tokens
    per block: rate = (new_tokens / target_calls - 1) / k.
    """
    if target_calls <= 0 or k <= 0:
        return None
    rate = (new_tokens / target_calls - 1.0) / k
    return max(0.0, min(1.0, rate))


class Telemetry:
    """The one object engines talk to.

    Always keeps a :class:`RingSink` (the watchdog flushes its tail on
    hang, and tests read it); adds a rank-0 JSONL file only when
    ``metrics_dir`` is set. ``due(step)`` is the emission gate the
    engines check *at their existing fetch points* — Telemetry never
    initiates a device fetch itself.
    """

    def __init__(
        self,
        metrics_dir: str | None = None,
        every: int = 1,
        run: str = "train",
        *,
        ring_capacity: int = 256,
        system_every: int = 5,  # system record per N step emissions; 0 = off
        flops_per_step: float | None = None,
        n_chips: int = 1,
        device_kind: str | None = None,
        extra_sinks: Iterable[MetricSink] = (),
    ):
        self.metrics_dir = metrics_dir
        self.every = max(1, int(every))
        self.run = run
        self.flops_per_step = flops_per_step
        self.n_chips = max(1, int(n_chips))
        self.device_kind = device_kind
        self.ring = RingSink(ring_capacity)
        sinks: list[MetricSink] = [self.ring, *extra_sinks]
        self.path: str | None = None
        if metrics_dir is not None:
            os.makedirs(metrics_dir, exist_ok=True)
            self.path = os.path.join(metrics_dir, METRICS_NAME)
            sinks.append(rank_zero(JsonlSink(self.path)))
        self._sink = MultiSink(sinks)
        self._system = _system.SystemMonitor()
        self._system_every = max(0, int(system_every))
        self._emits = 0
        self._last_step: int | None = None
        self._last_mono: float | None = None
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    def write_manifest(
        self, config: Any = None, mesh: Any = None, **extra: Any
    ) -> str | None:
        """Write ``manifest.json`` beside the metrics (no-op without a
        ``metrics_dir``; rank-gated inside)."""
        if self.metrics_dir is None:
            return None
        return _run_manifest.write_manifest(
            self.metrics_dir, config=config, mesh=mesh, run=self.run, **extra
        )

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._sink.close()

    # -- emission ----------------------------------------------------------

    def due(self, step: int) -> bool:
        """Should the engine emit (and therefore fetch) at this step?"""
        return step % self.every == 0

    def emit_step(self, step: int, **fields: Any) -> None:
        """Emit one per-step record. ``step_time_s`` is amortized over
        the steps elapsed since the previous emission, so any cadence
        still yields an honest per-step time; MFU derives from it when
        the engine declared ``flops_per_step`` on a known TPU."""
        now = time.monotonic()
        record: dict[str, Any] = {
            "kind": "step",
            "run": self.run,
            "step": int(step),
            "time": time.time(),
            # Wall+monotonic pair: obs/fleet.py anchors cross-process
            # alignment on monotonic when available (immune to clock
            # steps mid-run).
            "mono": now,
        }
        # generation/global-rank attribution so merged multi-process
        # step streams stay per-rank attributable (same stamping as
        # emit_event below).
        try:
            from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
                runtime_labels,
            )

            labels = runtime_labels()
            record["process_id"] = labels["process_id"]
            record["generation"] = labels["generation"]
            record["global_rank"] = labels["global_rank"]
        except Exception:  # stamping must never break telemetry
            pass
        step_time = None
        if self._last_mono is not None and self._last_step is not None:
            dsteps = int(step) - self._last_step
            if dsteps > 0:
                step_time = (now - self._last_mono) / dsteps
        self._last_mono, self._last_step = now, int(step)
        record["step_time_s"] = step_time
        if step_time and self.flops_per_step:
            record["mfu"] = _flops.mfu(
                self.flops_per_step / step_time / self.n_chips,
                self.device_kind or "",
            )
        record.update(fields)
        self._sink.emit(record)
        self._emits += 1
        if self._system_every and self._emits % self._system_every == 0:
            self.emit_system(step)

    def emit_system(self, step: int | None = None) -> None:
        record: dict[str, Any] = {
            "kind": "system",
            "run": self.run,
            "time": time.time(),
        }
        if step is not None:
            record["step"] = int(step)
        record.update(self._system.snapshot())
        self._sink.emit(record)

    def emit_event(self, event: str, **fields: Any) -> None:
        record: dict[str, Any] = {
            "kind": "event",
            "run": self.run,
            "event": event,
            "time": time.time(),
            "monotonic": time.monotonic(),
        }
        # process_id/generation attribution so merged multi-process
        # event streams stay per-rank attributable; explicit fields
        # (an already-stamped record forwarded by utils/failure.py)
        # win over the re-resolved labels.
        try:
            from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
                runtime_labels,
            )

            labels = runtime_labels()
            record["process_id"] = labels["process_id"]
            record["generation"] = labels["generation"]
        except Exception:  # stamping must never break telemetry
            pass
        record.update(fields)
        self._sink.emit(record)
