"""Render telemetry artifacts from the command line.

    python -m cs744_pytorch_distributed_tutorial_tpu.obs report <metrics_dir>
    python -m cs744_pytorch_distributed_tutorial_tpu.obs serve-report \\
        <trace_dir> [--check]
    python -m cs744_pytorch_distributed_tutorial_tpu.obs fleet-report \\
        <store_dir> [--check] [--no-artifacts]

``report`` reads a metrics dir (or a metrics.jsonl / phase_report.json
directly), filters the graftscope ``kind="phase"``/``"phase_summary"``
records, and prints the per-phase attribution table — same renderer
``bench.py --phase-breakdown`` prints live, usable after the fact on
any machine the JSONL landed on.

``serve-report`` summarizes a graftserve trace dir (``serve_cli.py
--trace-dir``: span/window/request JSONL + the Perfetto trace);
``--check`` additionally runs the span-consistency audit (no orphan,
unclosed, or overlapping spans; span sums reconcile with recorded
TTFT) and exits 1 on any problem — the CI serve-smoke gate.

``fleet-report`` merges everything a multi-process elastic run left in
its rendezvous store (per-rank stamp/metrics streams, events.jsonl,
heartbeat/death-note/world files) into one clock-aligned view: it
prints the graftfleet report (generations, incident timeline,
collective-skew attribution), writes ``fleet_trace.json`` (merged
Perfetto timeline) + ``fleet_report.json`` beside the store, and with
``--check`` runs the incident-consistency audit (deaths pair with
re-election + re-exec, no orphan generations, no span crosses a
generation seal), exiting 1 on any problem — the CI multihost-smoke
gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .metrics import METRICS_NAME
from .phases import phase_records_from_stream, render_phase_table


def _load_stream(path: str) -> list[dict]:
    """metrics dir, JSONL stream, or a phase_report.json array."""
    if os.path.isdir(path):
        for name in (METRICS_NAME, "phase_report.json"):
            candidate = os.path.join(path, name)
            if os.path.exists(candidate):
                path = candidate
                break
        else:
            raise FileNotFoundError(
                f"{path}: no {METRICS_NAME} or phase_report.json"
            )
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        obj = json.loads(text)
        if isinstance(obj, list):
            return [r for r in obj if isinstance(r, dict)]
        if isinstance(obj, dict):
            return [obj]
    except json.JSONDecodeError:
        pass
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m cs744_pytorch_distributed_tutorial_tpu.obs",
        description=__doc__,
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="render phase records as a table")
    rep.add_argument(
        "path", help="metrics dir, metrics.jsonl, or phase_report.json"
    )
    srv = sub.add_parser(
        "serve-report", help="summarize a graftserve trace dir"
    )
    srv.add_argument(
        "path",
        help="trace dir written by serve_cli --trace-dir, or a "
             "serve_spans.jsonl",
    )
    srv.add_argument(
        "--check",
        action="store_true",
        help="exit 1 on orphan/unclosed/overlapping spans or TTFT "
             "reconciliation drift",
    )
    flt = sub.add_parser(
        "fleet-report",
        help="merge a multi-process run dir into one timeline + audit",
    )
    flt.add_argument(
        "path",
        help="rendezvous store dir (launch.py --store / "
             "GRAFT_ELASTIC_TEST_STORE run dir)",
    )
    flt.add_argument(
        "--check",
        action="store_true",
        help="exit 1 on incident-consistency problems (unpaired deaths, "
             "orphan generations, seal-crossing spans)",
    )
    flt.add_argument(
        "--no-artifacts",
        action="store_true",
        help="print the report only; skip writing fleet_trace.json / "
             "fleet_report.json",
    )
    args = p.parse_args(argv)

    if args.cmd == "fleet-report":
        from .fleet import (
            ClockAligner,
            collective_skew,
            fleet_check,
            load_fleet_dir,
            render_fleet_report,
            write_fleet_artifacts,
        )

        if args.no_artifacts:
            data = load_fleet_dir(args.path)
            aligner = ClockAligner(data.barrier_stamps)
            skew = collective_skew(data, aligner)
            problems = fleet_check(data, aligner)
            print(render_fleet_report(data, skew, problems, aligner))
        else:
            result = write_fleet_artifacts(args.path)
            problems = result["problems"]
            print(result["text"])
            print(f"fleet-report: wrote {result['trace']}")
        if args.check:
            if problems:
                for prob in problems:
                    print(f"fleet check: {prob}", file=sys.stderr)
                return 1
            print("fleet check: OK")
        return 0

    if args.cmd == "serve-report":
        from .serve_trace import (
            check_spans,
            load_trace_dir,
            reconcile,
            render_serve_report,
        )

        data = load_trace_dir(args.path)
        print(render_serve_report(data))
        if args.check:
            problems = check_spans(data["spans"])
            problems += reconcile(data["spans"], data["requests"])
            if problems:
                for prob in problems:
                    print(f"serve-trace check: {prob}", file=sys.stderr)
                return 1
            print(
                f"serve-trace check: OK ({len(data['spans'])} spans, "
                f"{len(data['requests'])} requests)"
            )
        return 0

    records = phase_records_from_stream(_load_stream(args.path))
    if not records:
        print("no phase records found (run bench.py --phase-breakdown "
              "with --metrics-dir first)", file=sys.stderr)
        return 1
    print(render_phase_table(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
