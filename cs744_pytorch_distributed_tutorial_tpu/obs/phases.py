"""graftscope: per-phase step attribution for the training engines.

The fused train step is one XLA program — great for throughput (the
latency-hiding scheduler overlaps collectives with compute), useless
for attribution: nothing in a wall-clock number says how many ms/step
are forward, backward, gradient sync, or optimizer. This module builds
the missing instrument:

1. **Segmented step**: forward, forward+backward, grad-sync, and
   optimizer-apply compiled as SEPARATE jitted ``shard_map`` programs
   over the trainer's own mesh/specs, each timed under a device trace
   with a concrete-scalar fence (``capture_device_profile`` — the one
   trace-capture path; ``utils.profiling.device_op_breakdown`` is now a
   shim over it). Backward time is ``t(fwd+bwd) - t(fwd)``.
2. **Parity**: the segmented composition must reproduce the fused
   step's loss and post-step params within the ``test_sync_parity``
   tolerance discipline — attribution of a step that computes something
   else is worthless.
3. **Cost accounting**: per-phase flops / bytes-accessed via
   ``compiled.cost_analysis()``, per-phase MFU against the chip peak
   (``obs/flops.py``), analytic comm bytes for the sync phase
   (``parallel.sync.sync_wire_bytes`` — the TA003-audited model), and a
   compute/memory/comms roofline classification.
4. **``sync_exposed_ms``**: ``max(0, fused - (fwd+bwd + opt))`` — the
   sync time the fused step's scheduler did NOT hide behind compute.
   This is the explicit optimization target for the overlap work
   (ROADMAP item 2): overlap succeeds exactly when this goes to ~0
   while the isolated sync-segment time stays constant.

Restrictions (raise ``ValueError``, not wrong answers): segmentation
needs a separable explicit sync pass, so ``accum_steps == 1``, no
fsdp (its gradient reduction is the AD transpose of the parameter
all_gather, inserted inside backward), no fused_optimizer. zero1 IS
segmentable (fused or overlapped): the grad-sync segment runs the
per-bucket ``psum_scatter`` (or the int8+EF quantized wire) and the
optimizer segment runs the chunk updates PLUS the per-bucket delta
all_gathers — the gather is deliberately counted as optimizer time,
so ``sync_exposed_ms`` reports the unhidden scatter wire, the part
backward can hide. The LM engine additionally requires a pure
data-parallel, unsharded-optimizer layout (seq/tensor collectives
live inside the forward and cannot be carved out).
``'auto'``/``'none'`` reroute through the numerically-identical
explicit allreduce, exactly as the engine itself does under legacy
shard_map.

Segments compile with ``check_vma=False``: without the replication
analysis there are no AD-inserted collectives, so differentiating the
local loss yields purely local grads and the explicit sync segment is
the ONLY cross-device communication — which is the point.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Callable

import numpy as np

from . import flops as _flops

__all__ = [
    "PARITY_RTOL",
    "PARITY_ATOL",
    "PARITY_LOSS_RTOL",
    "DeviceProfile",
    "capture_device_profile",
    "compiled_costs",
    "roofline_classify",
    "PhaseStat",
    "PhaseReport",
    "build_cifar_segments",
    "build_lm_segments",
    "profile_phases",
    "profile_lm_phases",
    "render_phase_table",
    "phase_records_from_stream",
]

# The test_sync_parity tolerance discipline (tests/test_sync_parity.py):
# strategies must agree to float32 noise, and so must the segmented
# composition. Callers loosen these ONLY for sub-f32 compute dtypes.
PARITY_RTOL = 1e-5
PARITY_ATOL = 1e-6
PARITY_LOSS_RTOL = 1e-6

PHASE_NAMES = ("forward", "backward", "grad_sync", "optimizer")

# Ridge point (flops/byte) used by the roofline classifier when the
# device kind has no known peak pair: v5e's 197e12 / 819e9 ~= 240.
DEFAULT_RIDGE_FLOPS_PER_BYTE = 240.0


# ---------------------------------------------------------------------------
# Trace capture — THE shared path (device_op_breakdown shims onto this)
# ---------------------------------------------------------------------------


def _fence(out: Any) -> None:
    """Force completion of ``out`` by fetching one concrete scalar: a
    host round-trip cannot finish before the computation it depends on.
    NOT ``block_until_ready`` — unreliable as a completion fence on the
    tunneled TPU backend (bench.py, measured ~190x inflation)."""
    import jax

    leaf = jax.tree.leaves(out)[0]
    float(leaf.ravel().astype("float32")[0])


@dataclasses.dataclass
class DeviceProfile:
    """One timed region: device time (trace interval union), fenced host
    wall time, and the top op rows — all per iteration."""

    device_ms: float  # 0.0 when the trace shows no device lanes (CPU)
    wall_ms: float
    op_rows: list  # [(ms_per_iter, op_name), ...] descending
    iters: int

    @property
    def clock(self) -> str:
        """Which clock ``best_ms`` reports: ``"device"`` when the trace
        yielded device lanes, else the fenced ``"wall"`` fallback."""
        return "device" if self.device_ms > 0.0 else "wall"

    def best_ms(self) -> float:
        return self.device_ms if self.device_ms > 0.0 else self.wall_ms


def _parse_trace(trace_dir: str, iters: int, top: int):
    """Newest Perfetto trace under ``trace_dir`` -> (device_ms_per_iter,
    top op rows). Device total is the per-PID interval UNION of device-
    lane events: trace rows nest (a jit_ program contains its op rows)
    and XLA puts the module event and its ops on different threads of
    the same device process, so neither a flat sum nor per-(pid, tid)
    lanes would be correct."""
    import collections
    import glob
    import gzip
    import os

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    )
    if not paths:
        raise RuntimeError(f"no trace produced under {trace_dir}")
    with gzip.open(paths[-1]) as f:
        events = json.load(f)["traceEvents"]
    pids: dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    durs: collections.Counter = collections.Counter()
    by_lane: dict = collections.defaultdict(list)
    for e in events:
        pname = pids.get(e.get("pid"), "")
        device_lane = (
            "TPU" in pname or "device" in pname.lower() or "/gpu" in pname
        )
        if e.get("ph") == "X" and e.get("dur") and device_lane:
            durs[e["name"]] += e["dur"]
            by_lane[e.get("pid")].append((e.get("ts", 0.0), e["dur"]))
    rows = sorted(
        ((v / iters / 1e3, k) for k, v in durs.items()), reverse=True
    )
    total_us = 0.0
    for lane in by_lane.values():
        # Ties sort by -dur so a parent sharing its first child's start
        # timestamp wins the top-level slot.
        lane.sort(key=lambda td: (td[0], -td[1]))
        end = float("-inf")
        for ts, dur in lane:
            if ts >= end:
                total_us += dur
                end = ts + dur
            elif ts + dur > end:
                # Overlapping but not nested (a DMA straddling a module
                # boundary): count only the tail — a true interval union.
                total_us += ts + dur - end
                end = ts + dur
    return total_us / iters / 1e3, rows[:top]


def capture_device_profile(
    fn: Callable,
    *args: Any,
    iters: int = 3,
    top: int = 20,
    trace_dir: str | None = None,
) -> DeviceProfile:
    """Run ``fn(*args)`` ``iters`` times under a profiler trace; return
    per-iteration device time, fenced host wall time, and the top op
    rows. Compiles (first call) OUTSIDE the trace; completion is fenced
    by a concrete-scalar fetch. The one trace-capture path shared by
    graftscope and ``utils.profiling.device_op_breakdown``."""
    import shutil
    import tempfile

    import jax

    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    _fence(fn(*args))  # compile + warm outside the trace
    owns_dir = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="graftscope_trace_")
    try:
        jax.profiler.start_trace(d)
        try:
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = fn(*args)
            _fence(out)
            wall_ms = (time.perf_counter() - t0) * 1e3 / iters
        finally:
            jax.profiler.stop_trace()
        device_ms, rows = _parse_trace(d, iters, top)
        return DeviceProfile(
            device_ms=device_ms, wall_ms=wall_ms, op_rows=rows, iters=iters
        )
    finally:
        if owns_dir:
            shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# Cost analysis + roofline
# ---------------------------------------------------------------------------


def compiled_costs(compiled: Any) -> dict[str, float | None]:
    """``{'flops': F, 'bytes_accessed': B}`` from a compiled
    executable's ``cost_analysis()`` (per-device module costs). Handles
    both the list-of-dicts (jax 0.4.x) and plain-dict returns; absent
    keys map to None — never fabricated."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": None, "bytes_accessed": None}
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed", ca.get("bytes_accessed"))
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": (
            float(bytes_accessed) if bytes_accessed is not None else None
        ),
    }


def roofline_classify(
    flops: float | None,
    bytes_accessed: float | None,
    device_kind: str | None,
    *,
    comm_bytes: float = 0.0,
) -> str:
    """'comms' | 'compute' | 'memory' | 'unknown'.

    A phase that puts bytes on the wire is comms-bound by construction
    (its time scales with the interconnect, not the roofline). Otherwise
    classify by arithmetic intensity against the chip's ridge point
    (peak_flops / peak_hbm_bw) when both peaks are known, else the
    documented v5e default ridge."""
    if comm_bytes and comm_bytes > 0:
        return "comms"
    if not flops or not bytes_accessed:
        return "unknown"
    peak_f = _flops.peak_flops_per_chip(device_kind or "")
    peak_b = _flops.peak_hbm_bytes_per_sec(device_kind or "")
    ridge = (
        peak_f / peak_b if (peak_f and peak_b) else DEFAULT_RIDGE_FLOPS_PER_BYTE
    )
    return "compute" if flops / bytes_accessed >= ridge else "memory"


# ---------------------------------------------------------------------------
# Report types
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PhaseStat:
    name: str
    device_ms: float
    wall_ms: float
    clock: str
    flops: float | None
    bytes_accessed: float | None
    comm_bytes: float
    mfu: float | None
    roofline: str

    def best_ms(self) -> float:
        return self.device_ms if self.device_ms > 0.0 else self.wall_ms


@dataclasses.dataclass
class PhaseReport:
    """The graftscope deliverable: per-phase stats + the fused-vs-
    segmented comparison, serializable as flat telemetry records."""

    phases: list[PhaseStat]
    fused_ms: float
    fused_clock: str
    segmented_total_ms: float
    sync_exposed_ms: float
    parity_ok: bool
    loss_fused: float
    loss_segmented: float
    max_param_abs_diff: float
    n_chips: int
    device_kind: str
    batch: int | None
    iters: int

    def phase(self, name: str) -> PhaseStat:
        for p in self.phases:
            if p.name == name:
                return p
        raise KeyError(name)

    def records(self, run: str = "phase") -> list[dict[str, Any]]:
        """Flat sink-ready records: one ``kind="phase"`` per phase plus
        one ``kind="phase_summary"``."""
        recs: list[dict[str, Any]] = []
        for p in self.phases:
            recs.append(
                {
                    "kind": "phase",
                    "run": run,
                    "phase": p.name,
                    "device_ms": round(p.device_ms, 4),
                    "wall_ms": round(p.wall_ms, 4),
                    "clock": p.clock,
                    "flops": p.flops,
                    "bytes_accessed": p.bytes_accessed,
                    "comm_bytes": p.comm_bytes,
                    "mfu": p.mfu,
                    "roofline": p.roofline,
                    "iters": self.iters,
                }
            )
        recs.append(
            {
                "kind": "phase_summary",
                "run": run,
                "fused_step_ms": round(self.fused_ms, 4),
                "fused_clock": self.fused_clock,
                "segmented_total_ms": round(self.segmented_total_ms, 4),
                "sync_exposed_ms": round(self.sync_exposed_ms, 4),
                "parity_ok": self.parity_ok,
                "loss_fused": self.loss_fused,
                "loss_segmented": self.loss_segmented,
                "max_param_abs_diff": self.max_param_abs_diff,
                "n_chips": self.n_chips,
                "device_kind": self.device_kind,
                "batch": self.batch,
                "iters": self.iters,
            }
        )
        return recs

    def table(self) -> str:
        return render_phase_table(self.records())


def _fmt_num(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_phase_table(records: list[dict[str, Any]]) -> str:
    """Render ``kind="phase"``/``kind="phase_summary"`` records (any
    mixed stream; other kinds are ignored) into the phase table — shared
    by ``python -m ...obs report``, ``bench.py --phase-breakdown`` and
    ``benchmarks/metrics_summary.py``."""
    phases = [r for r in records if r.get("kind") == "phase"]
    summaries = [r for r in records if r.get("kind") == "phase_summary"]
    if not phases and not summaries:
        return "(no phase records)"
    cols = ("phase", "ms", "clock", "flops", "bytes", "comm B", "MFU", "roofline")
    rows = [cols]
    for r in phases:
        ms = r.get("device_ms") if r.get("clock") == "device" else r.get("wall_ms")
        rows.append(
            (
                str(r.get("phase")),
                _fmt_num(ms),
                str(r.get("clock", "-")),
                _fmt_num(r.get("flops")),
                _fmt_num(r.get("bytes_accessed")),
                _fmt_num(r.get("comm_bytes")),
                _fmt_num(r.get("mfu")),
                str(r.get("roofline", "-")),
            )
        )
    widths = [max(len(row[i]) for row in rows) for i in range(len(cols))]
    lines = [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        for row in rows
    ]
    for s in summaries:
        lines.append("")
        lines.append(
            f"fused step: {_fmt_num(s.get('fused_step_ms'))} ms "
            f"({s.get('fused_clock', '-')})   segmented total: "
            f"{_fmt_num(s.get('segmented_total_ms'))} ms"
        )
        lines.append(
            f"sync_exposed_ms: {_fmt_num(s.get('sync_exposed_ms'))}   "
            f"parity_ok: {s.get('parity_ok')}   "
            f"loss fused/segmented: {_fmt_num(s.get('loss_fused'))}/"
            f"{_fmt_num(s.get('loss_segmented'))}"
        )
    return "\n".join(lines)


def phase_records_from_stream(
    records: list[dict[str, Any]],
) -> list[dict[str, Any]]:
    """Filter a telemetry stream down to the graftscope records."""
    return [
        r for r in records if r.get("kind") in ("phase", "phase_summary")
    ]


# ---------------------------------------------------------------------------
# Parity
# ---------------------------------------------------------------------------


def _check_parity(
    loss_fused: float,
    loss_segmented: float,
    params_fused: Any,
    params_segmented: Any,
    *,
    rtol: float,
    atol: float,
    loss_rtol: float,
) -> tuple[bool, float]:
    """(parity_ok, max param abs diff) under the sync-parity discipline."""
    import jax

    ok = abs(loss_fused - loss_segmented) <= max(
        loss_rtol * abs(loss_fused), 1e-12
    )
    max_diff = 0.0
    lf = jax.tree.leaves(params_fused)
    ls = jax.tree.leaves(params_segmented)
    for a, b in zip(lf, ls):
        a = np.asarray(jax.device_get(a), dtype=np.float64)
        b = np.asarray(jax.device_get(b), dtype=np.float64)
        if a.size:
            max_diff = max(max_diff, float(np.max(np.abs(a - b))))
        if not np.allclose(a, b, rtol=rtol, atol=atol):
            ok = False
    return ok, max_diff


def _parity_tols(compute_dtype: str) -> tuple[float, float, float]:
    """(rtol, atol, loss_rtol): the f32 sync-parity tolerances, loosened
    when the compute dtype rounds harder than f32 — fused and segmented
    programs fuse differently, so bf16 accumulation order differs."""
    if compute_dtype in ("float32", "f32"):
        return PARITY_RTOL, PARITY_ATOL, PARITY_LOSS_RTOL
    return 1e-2, 1e-3, 1e-2


# ---------------------------------------------------------------------------
# CIFAR engine segments
# ---------------------------------------------------------------------------


class CifarSegments:
    """The four phase programs of one CIFAR train step, plus a
    non-donating clone of the fused step for honest same-inputs timing
    (the engine's ``train_step`` donates its state and would delete the
    timing inputs on the first call)."""

    def __init__(self, trainer: Any):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        import optax

        from cs744_pytorch_distributed_tutorial_tpu.data.augment import (
            augment_train_batch,
            eval_batch,
        )
        from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
            DATA_AXIS,
        )
        from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
            sync_grads,
            sync_grads_compressed,
        )
        from cs744_pytorch_distributed_tutorial_tpu.train.engine import (
            _smoothed_xent,
        )
        from cs744_pytorch_distributed_tutorial_tpu.train.state import (
            TrainState,
        )

        from cs744_pytorch_distributed_tutorial_tpu.parallel import (
            buckets as _B,
            overlap as _OV,
        )

        cfg = trainer.cfg
        if cfg.accum_steps != 1:
            raise ValueError(
                "graftscope segmentation requires accum_steps=1: with "
                "accumulation the sync runs inside the microbatch scan and "
                "cannot be carved into its own program"
            )
        if trainer._fsdp or cfg.fused_optimizer:
            raise ValueError(
                f"graftscope segmentation does not support sync={cfg.sync!r}/"
                f"fused_optimizer={cfg.fused_optimizer}: fsdp's gradient "
                "reduction is the AD transpose of its parameter all_gather "
                "(inserted inside backward) and the fused kernel is one "
                "whole-tree Pallas call — neither has a separable sync "
                "phase. allreduce/ring/zero1 (fused or overlapped) are "
                "segmentable"
            )
        if trainer._zero1 and not (
            trainer._bucket_bytes and trainer.axis_size > 1
        ):
            raise ValueError(
                "graftscope zero1 segmentation requires the bucketed "
                "multi-device path (sync_bucket_mb > 0, num_devices > 1): "
                "the per-leaf fallback has no bucket lanes to carve"
            )
        self.trainer = trainer
        self.compress = trainer._compress
        self.overlap = getattr(trainer, "_overlap", False)
        self.zero1 = trainer._zero1
        axis_size = trainer.axis_size
        model, tx = trainer.model, trainer.tx
        bucket_bytes = trainer._bucket_bytes
        # 'auto'/'none' have no hand-traced sync pass; the explicit
        # allreduce is numerically identical (the engine itself reroutes
        # them this way under legacy shard_map).
        explicit_sync = (
            "allreduce" if cfg.sync in ("auto", "none") else cfg.sync
        )
        wire_name = (
            "int8_ring" if trainer._compress_ring else "int8_allreduce"
        )
        state_specs = trainer._state_specs()

        def local_loss_fn(state, images, labels, base_key):
            """The engine's exact key/augment/loss recipe, closed over a
            single microbatch — KEEP IN SYNC with engine.local_train_step."""
            key = jax.random.fold_in(base_key, state.step)
            key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
            x = (
                augment_train_batch(key, images)
                if cfg.augment
                else eval_batch(images)
            )
            drop_key = jax.random.fold_in(key, 7)
            local_stats = jax.tree.map(lambda a: a[0], state.batch_stats)

            def loss_fn(p):
                logits, mutated = model.apply(
                    {"params": p, "batch_stats": local_stats},
                    x,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": drop_key},
                )
                loss = _smoothed_xent(logits, labels, cfg.label_smoothing)
                return loss, mutated["batch_stats"]

            return loss_fn

        def seg_forward(state, images, labels, base_key):
            loss_fn = local_loss_fn(state, images, labels, base_key)
            local, _ = loss_fn(state.params)
            return lax.pmean(local, DATA_AXIS)

        def seg_grads(state, images, labels, base_key):
            # check_vma=False: no replication analysis, so grads come out
            # purely LOCAL (no AD-inserted psum) — the state after the
            # reference's loss.backward() and before its sync loop.
            loss_fn = local_loss_fn(state, images, labels, base_key)
            (local, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            return (
                lax.pmean(local, DATA_AXIS),
                jax.tree.map(lambda g: g[None], grads),
                jax.tree.map(lambda s: s[None], new_stats),
            )

        def seg_sync(grads_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            return sync_grads(
                g,
                explicit_sync,
                DATA_AXIS,
                axis_size,
                bucket_bytes=bucket_bytes,
            )

        def seg_sync_compressed(grads_stacked, ef_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            e = jax.tree.map(lambda a: a[0], ef_stacked)
            synced, ef_out = sync_grads_compressed(
                g,
                e,
                wire_name,
                DATA_AXIS,
                axis_size,
                bucket_bytes=bucket_bytes,
            )
            return synced, jax.tree.map(lambda a: a[None], ef_out)

        def seg_opt(state, synced, stats_stacked, ef_stacked):
            updates, new_opt = tx.update(
                synced, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(
                step=state.step + 1,
                params=new_params,
                batch_stats=stats_stacked,
                opt_state=new_opt,
                ef=ef_stacked,
            )

        # Overlapped-schedule segments (parallel/overlap.py): the same
        # reverse-order bucket layout and per-bucket kernels the fused
        # overlapped step runs, split at the sync/apply boundary. Buckets
        # are independent, so all-syncs-then-all-applies is bitwise the
        # fused interleaving; the per-bucket named scopes give the sync
        # segment's trace the same bucketNN lanes as the fused program.
        ov_name = wire_name if self.compress else explicit_sync

        def ov_layout(tree):
            return _OV.overlap_layout(
                tree,
                explicit_sync,
                axis_size,
                bucket_bytes,
                compressed=self.compress,
            )

        def seg_sync_overlap(grads_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            layout = ov_layout(g)
            bufs = _B.flatten_for_sync(g, layout)
            synced = []
            for k, buf in enumerate(bufs):
                with jax.named_scope(
                    f"graftscope/sync/overlap/{ov_name}/bucket{k:02d}"
                ):
                    synced.append(
                        _OV.sync_bucket(buf, explicit_sync, DATA_AXIS, axis_size)
                    )
            return _B.unflatten(synced, layout)

        def seg_sync_overlap_compressed(grads_stacked, ef_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            e = jax.tree.map(lambda a: a[0], ef_stacked)
            layout = ov_layout(g)
            g_bufs = _B.flatten_for_sync(g, layout)
            e_bufs = _B.flatten_for_sync(e, layout)
            synced, new_e = [], []
            for k, (gbuf, ebuf) in enumerate(zip(g_bufs, e_bufs)):
                with jax.named_scope(
                    f"graftscope/sync/overlap/{ov_name}/bucket{k:02d}"
                ):
                    s, resid = _OV.sync_bucket_compressed(
                        gbuf, ebuf, ov_name, DATA_AXIS, axis_size
                    )
                synced.append(s)
                new_e.append(resid)
            ef_out = _B.unflatten(new_e, layout)
            return (
                _B.unflatten(synced, layout),
                jax.tree.map(lambda a: a[None], ef_out),
            )

        def seg_opt_overlap(state, synced, stats_stacked, ef_stacked):
            trace, rebuild = _OV.split_momentum(state.opt_state)
            layout = ov_layout(synced)
            p_bufs = _B.flatten_for_sync(state.params, layout)
            t_bufs = _B.flatten_for_sync(trace, layout)
            s_bufs = _B.flatten_for_sync(synced, layout)
            new_p, new_t = [], []
            for k, (p, t, s) in enumerate(zip(p_bufs, t_bufs, s_bufs)):
                with jax.named_scope(
                    f"graftscope/optimizer/overlap/bucket{k:02d}"
                ):
                    pn, tn = _OV.apply_bucket(
                        p,
                        t,
                        s,
                        lr=cfg.learning_rate,
                        momentum=cfg.momentum,
                        weight_decay=cfg.weight_decay,
                    )
                new_p.append(pn)
                new_t.append(tn)
            return TrainState(
                step=state.step + 1,
                params=_B.unflatten(new_p, layout),
                batch_stats=stats_stacked,
                opt_state=rebuild(_B.unflatten(new_t, layout)),
                ef=ef_stacked,
            )

        # ZeRO-1 segments: the sharded optimizer's step carved at the
        # scatter boundary — KEEP IN SYNC with parallel/zero.py
        # Zero1SGD._apply_bucketed (same bucket layout, same chunk rule,
        # same lane names). seg_sync_zero1 runs each bucket's
        # psum_scatter (or the int8+EF quantized wire) and returns the
        # device-owned mean-gradient rows; seg_opt_zero1 runs the chunk
        # updates AND the per-bucket delta all_gathers. The gather is
        # deliberately counted as optimizer time: the scatter wire is
        # what the overlapped schedule hides under backward, so
        # sync_exposed_ms reports the UNHIDDEN scatter.
        def zero1_layout(tree):
            return _B.bucket_layout(
                tree, bucket_bytes, rows=axis_size, reverse=self.overlap
            )

        def seg_sync_zero1(grads_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            layout = zero1_layout(g)
            bufs = _B.flatten_for_sync(g, layout)
            rows = []
            for k, buf in enumerate(bufs):
                with jax.named_scope(
                    f"graftscope/sync/overlap_rs/zero1/bucket{k:02d}"
                ):
                    rows.append(
                        (
                            lax.psum_scatter(
                                buf, DATA_AXIS, scatter_dimension=0
                            )
                            / axis_size
                        )[None]
                    )
            return tuple(rows)

        def seg_sync_zero1_compressed(grads_stacked, ef_stacked):
            from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
                _int8_allreduce_flat,
            )

            g = jax.tree.map(lambda a: a[0], grads_stacked)
            e = jax.tree.map(lambda a: a[0], ef_stacked)
            layout = zero1_layout(g)
            g_bufs = _B.flatten_for_sync(g, layout)
            e_bufs = _B.flatten_for_sync(e, layout)
            idx = lax.axis_index(DATA_AXIS)
            rows, new_e = [], []
            for k, (gbuf, ebuf) in enumerate(zip(g_bufs, e_bufs)):
                cols = gbuf.shape[-1]
                with jax.named_scope(
                    f"graftscope/sync/overlap_rs/zero1/bucket{k:02d}"
                ):
                    b = gbuf.reshape(-1).astype(jnp.float32) + ebuf.reshape(
                        -1
                    ).astype(jnp.float32)
                    mean, resid = _int8_allreduce_flat(
                        b, DATA_AXIS, axis_size
                    )
                new_e.append(resid.reshape(axis_size, cols))
                rows.append(
                    lax.dynamic_index_in_dim(
                        mean.reshape(axis_size, cols).astype(gbuf.dtype),
                        idx,
                        0,
                        keepdims=True,
                    )
                )
            ef_out = _B.unflatten(new_e, layout)
            return tuple(rows), jax.tree.map(lambda a: a[None], ef_out)

        def seg_opt_zero1(state, scattered, stats_stacked, ef_stacked):
            idx = lax.axis_index(DATA_AXIS)
            leaves_p, treedef = jax.tree.flatten(state.params)
            leaves_m = jax.tree.leaves(state.opt_state)
            layout = zero1_layout(state.params)
            by_bucket = [[] for _ in layout.bucket_cols]
            for i, slot in enumerate(layout.slots):
                by_bucket[slot.bucket].append((slot.offset, i, slot))
            new_p = [None] * len(leaves_p)
            new_m = [None] * len(leaves_p)
            for k, group in enumerate(by_bucket):
                group.sort(key=lambda t: t[0])
                g_mine = scattered[k][0]
                deltas = []
                with jax.named_scope(
                    f"graftscope/optimizer/overlap/bucket{k:02d}"
                ):
                    for off, i, slot in group:
                        chunk = slot.size
                        p = leaves_p[i]
                        pad = axis_size * chunk - p.size
                        p2d = jnp.pad(p.ravel(), (0, pad)).reshape(
                            axis_size, chunk
                        )
                        p_mine = lax.dynamic_index_in_dim(
                            p2d, idx, 0, keepdims=False
                        )
                        m_new, delta_mine = tx._sgd_chunk_update(
                            p_mine,
                            leaves_m[i].reshape(chunk),
                            g_mine[off : off + chunk],
                        )
                        deltas.append(delta_mine)
                        new_m[i] = m_new.reshape(1, chunk)
                with jax.named_scope(
                    f"graftscope/sync/overlap_ag/zero1/bucket{k:02d}"
                ):
                    delta_buf = lax.all_gather(
                        jnp.concatenate(deltas), DATA_AXIS, axis=0
                    )
                for off, i, slot in group:
                    chunk = slot.size
                    p = leaves_p[i]
                    delta_flat = delta_buf[:, off : off + chunk].reshape(
                        axis_size * chunk
                    )[: p.size]
                    new_p[i] = p + delta_flat.reshape(p.shape)
            return TrainState(
                step=state.step + 1,
                params=jax.tree.unflatten(treedef, new_p),
                batch_stats=stats_stacked,
                opt_state=jax.tree.unflatten(treedef, new_m),
                ef=ef_stacked,
            )

        if self.zero1:
            seg_sync = seg_sync_zero1
            seg_sync_compressed = seg_sync_zero1_compressed
            seg_opt = seg_opt_zero1
        elif self.overlap:
            seg_sync = seg_sync_overlap
            seg_sync_compressed = seg_sync_overlap_compressed
            seg_opt = seg_opt_overlap

        def sm(f, in_specs, out_specs):
            return jax.jit(
                jax.shard_map(
                    f,
                    mesh=trainer.mesh,
                    in_specs=in_specs,
                    out_specs=out_specs,
                    check_vma=False,
                )
            )

        batch_in = (state_specs, P(DATA_AXIS), P(DATA_AXIS), P())
        self.forward = sm(seg_forward, batch_in, P())
        self.grads = sm(seg_grads, batch_in, (P(), P(DATA_AXIS), P(DATA_AXIS)))
        # zero1's sync segment yields device-OWNED rows (one [1, cols]
        # shard per bucket), not a replicated mean tree — spec them
        # sharded over data; the prefix P(DATA_AXIS) covers the whole
        # per-bucket tuple.
        synced_spec = P(DATA_AXIS) if self.zero1 else P()
        if self.compress:
            self.sync = sm(
                seg_sync_compressed,
                (P(DATA_AXIS), P(DATA_AXIS)),
                (synced_spec, P(DATA_AXIS)),
            )
        else:
            self.sync = sm(seg_sync, (P(DATA_AXIS),), synced_spec)
        self.opt = sm(
            seg_opt,
            (state_specs, synced_spec, P(DATA_AXIS), state_specs.ef),
            state_specs,
        )
        # Non-donating fused step over the SAME mapped function the
        # engine jits (train/engine.py exposes it as mapped_train).
        self.fused = jax.jit(trainer.mapped_train)

    def segmented_step(self, state, x, y, key):
        """Compose the segments into one full step: (new_state, loss)."""
        loss, g_st, stats = self.grads(state, x, y, key)
        if self.compress:
            synced, ef = self.sync(g_st, state.ef)
        else:
            synced = self.sync(g_st)
            ef = state.ef
        return self.opt(state, synced, stats, ef), loss


def build_cifar_segments(trainer: Any) -> CifarSegments:
    return CifarSegments(trainer)


# ---------------------------------------------------------------------------
# LM engine segments
# ---------------------------------------------------------------------------


class LMSegments:
    """Phase programs for the LM engine, pure data-parallel layouts
    only: seq/tensor collectives live inside the forward (ring hops,
    Megatron f/g boundaries) and cannot be carved into a sync phase."""

    def __init__(self, trainer: Any):
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        import optax

        from cs744_pytorch_distributed_tutorial_tpu.parallel import (
            buckets as _B,
            overlap as _OV,
        )
        from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
            DATA_AXIS,
        )
        from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
            sync_grads_compressed,
        )
        from cs744_pytorch_distributed_tutorial_tpu.train.lm import (
            SEQ_AXIS,
        )

        cfg = trainer.cfg
        if cfg.accum_steps != 1:
            raise ValueError(
                "graftscope segmentation requires accum_steps=1"
            )
        if trainer._zero1_opt is not None or cfg.fsdp:
            raise ValueError(
                "graftscope LM segmentation does not support zero1/fsdp: "
                "the DP reduction is fused into the sharded update (and "
                "for fsdp it is the AD transpose of the parameter "
                "all_gather). Time those schedules with the CIFAR engine's "
                "zero1 segments, or from a profile_dir trace — the "
                "overlapped schedule labels per-bucket lanes "
                "(graftscope/sync/overlap_rs/*, graftscope/optimizer/"
                "overlap/*, graftscope/sync/overlap_ag/*)"
            )
        if (
            trainer.seq_size > 1
            or getattr(trainer, "tensor_size", 1) > 1
            or getattr(trainer, "expert_parallel", False)
        ):
            raise ValueError(
                "graftscope LM segmentation requires a pure data-parallel "
                "layout (seq_parallel=1, no tensor axis, no expert "
                "parallelism): other axes' collectives run inside the "
                "forward and cannot be separated into a sync phase"
            )
        self.trainer = trainer
        self.compress = trainer._compress
        self.overlap = getattr(trainer, "_overlap", False)
        model, tx = trainer.model, trainer.tx
        data_size = trainer.data_size
        bucket_bytes = trainer._bucket_bytes
        param_specs = trainer.param_specs
        batch_spec = P(DATA_AXIS, SEQ_AXIS)
        if self.compress:
            tx_opt_specs, _ef_spec = trainer.opt_specs
        else:
            tx_opt_specs = trainer.opt_specs

        fused_xent = cfg.fused_xent
        xent_interpret = trainer._flash_interpret
        smoothing = cfg.label_smoothing
        dropout = cfg.dropout_rate
        seed = cfg.seed
        aux_coef = cfg.moe_aux_coef

        def loss_fn(p, toks, tgts, drop_key):
            """The LM engine's exact local loss — KEEP IN SYNC with
            lm._build_steps.loss_fn (same smoothing/fused-xent/MoE-aux
            objective; the monitoring-only sown metrics are dropped)."""
            apply_kw = (
                dict(rngs={"dropout": drop_key}, deterministic=False)
                if dropout > 0.0
                else {}
            )
            logits, mut = model.apply(
                {"params": p}, toks, mutable=["losses", "metrics"], **apply_kw
            )
            if fused_xent:
                from cs744_pytorch_distributed_tutorial_tpu.ops.fused_xent import (
                    fused_cross_entropy,
                )

                v = logits.shape[-1]
                ce = fused_cross_entropy(
                    logits.reshape(-1, v),
                    tgts.reshape(-1),
                    interpret=xent_interpret,
                ).mean()
            else:
                from cs744_pytorch_distributed_tutorial_tpu.train.engine import (
                    _smoothed_xent,
                )

                ce = _smoothed_xent(logits, tgts, smoothing)
            from cs744_pytorch_distributed_tutorial_tpu.models.moe import (
                moe_aux_loss,
            )

            return ce + aux_coef * moe_aux_loss(mut)

        def drop_key_for(step):
            k = jax.random.fold_in(jax.random.key(seed), step)
            k = jax.random.fold_in(k, lax.axis_index(DATA_AXIS))
            return jax.random.fold_in(k, lax.axis_index(SEQ_AXIS))

        def mean_over_replicas(x):
            return lax.pmean(lax.pmean(x, DATA_AXIS), SEQ_AXIS)

        def seg_forward(params, tokens, targets, step):
            local = loss_fn(params, tokens, targets, drop_key_for(step))
            return mean_over_replicas(local)

        def seg_grads(params, tokens, targets, step):
            local, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets, drop_key_for(step)
            )
            return (
                mean_over_replicas(local),
                jax.tree.map(lambda g: g[None], grads),
            )

        def seg_sync(grads_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            # Pure DP: sync_grad reduces to the data/seq pmean pair
            # (seq axis is 1-sized here, so that pmean is identity —
            # kept for exact numerical equivalence with the fused step).
            return jax.tree.map(
                lambda g: lax.pmean(lax.pmean(g, DATA_AXIS), SEQ_AXIS), g
            )

        def seg_sync_compressed(grads_stacked, ef_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            e = jax.tree.map(lambda a: a[0], ef_stacked)
            synced, ef_out = sync_grads_compressed(
                g,
                e,
                "int8_allreduce",
                DATA_AXIS,
                data_size,
                bucket_bytes=bucket_bytes,
            )
            return synced, jax.tree.map(lambda a: a[None], ef_out)

        def seg_opt(params, opt_state, synced):
            updates, new_opt = tx.update(synced, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        # Overlapped-schedule segments — see CifarSegments for the
        # bitwise argument (independent buckets: all-syncs-then-all-
        # applies equals the fused interleaving).
        ov_name = "int8_allreduce" if self.compress else "allreduce"

        def ov_layout(tree):
            return _OV.overlap_layout(
                tree,
                "allreduce",
                data_size,
                bucket_bytes,
                compressed=self.compress,
            )

        def seg_sync_overlap(grads_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            layout = ov_layout(g)
            bufs = _B.flatten_for_sync(g, layout)
            synced = []
            for k, buf in enumerate(bufs):
                with jax.named_scope(
                    f"graftscope/sync/overlap/{ov_name}/bucket{k:02d}"
                ):
                    synced.append(
                        _OV.sync_bucket(buf, "allreduce", DATA_AXIS, data_size)
                    )
            return _B.unflatten(synced, layout)

        def seg_sync_overlap_compressed(grads_stacked, ef_stacked):
            g = jax.tree.map(lambda a: a[0], grads_stacked)
            e = jax.tree.map(lambda a: a[0], ef_stacked)
            layout = ov_layout(g)
            g_bufs = _B.flatten_for_sync(g, layout)
            e_bufs = _B.flatten_for_sync(e, layout)
            synced, new_e = [], []
            for k, (gbuf, ebuf) in enumerate(zip(g_bufs, e_bufs)):
                with jax.named_scope(
                    f"graftscope/sync/overlap/{ov_name}/bucket{k:02d}"
                ):
                    s, resid = _OV.sync_bucket_compressed(
                        gbuf, ebuf, ov_name, DATA_AXIS, data_size
                    )
                synced.append(s)
                new_e.append(resid)
            ef_out = _B.unflatten(new_e, layout)
            return (
                _B.unflatten(synced, layout),
                jax.tree.map(lambda a: a[None], ef_out),
            )

        def seg_opt_overlap(params, opt_state, synced):
            trace, rebuild = _OV.split_momentum(opt_state)
            layout = ov_layout(synced)
            p_bufs = _B.flatten_for_sync(params, layout)
            t_bufs = _B.flatten_for_sync(trace, layout)
            s_bufs = _B.flatten_for_sync(synced, layout)
            new_p, new_t = [], []
            for k, (p, t, s) in enumerate(zip(p_bufs, t_bufs, s_bufs)):
                with jax.named_scope(
                    f"graftscope/optimizer/overlap/bucket{k:02d}"
                ):
                    pn, tn = _OV.apply_bucket(
                        p,
                        t,
                        s,
                        lr=cfg.learning_rate,
                        momentum=cfg.momentum,
                        weight_decay=cfg.weight_decay,
                    )
                new_p.append(pn)
                new_t.append(tn)
            return _B.unflatten(new_p, layout), rebuild(
                _B.unflatten(new_t, layout)
            )

        if self.overlap:
            seg_sync = seg_sync_overlap
            seg_sync_compressed = seg_sync_overlap_compressed
            seg_opt = seg_opt_overlap

        def sm(f, in_specs, out_specs):
            return jax.jit(
                jax.shard_map(
                    f,
                    mesh=trainer.mesh,
                    in_specs=in_specs,
                    out_specs=out_specs,
                    check_vma=False,
                )
            )

        batch_in = (param_specs, batch_spec, batch_spec, P())
        self.forward = sm(seg_forward, batch_in, P())
        self.grads = sm(seg_grads, batch_in, (P(), P(DATA_AXIS)))
        if self.compress:
            self.sync = sm(
                seg_sync_compressed,
                (P(DATA_AXIS), P(DATA_AXIS)),
                (P(), P(DATA_AXIS)),
            )
        else:
            self.sync = sm(seg_sync, (P(DATA_AXIS),), P())
        self.opt = sm(
            seg_opt,
            (param_specs, tx_opt_specs, P()),
            (param_specs, tx_opt_specs),
        )
        self.fused = jax.jit(trainer.mapped_train)

    def segmented_step(self, params, opt_state, x, y, step):
        """((new_params, new_opt_state), loss) — ``opt_state`` in the
        engine's own layout ((tx_state, ef) when compressed)."""
        loss, g_st = self.grads(params, x, y, step)
        if self.compress:
            tx_state, ef = opt_state
            synced, new_ef = self.sync(g_st, ef)
            new_params, new_tx = self.opt(params, tx_state, synced)
            return (new_params, (new_tx, new_ef)), loss
        synced = self.sync(g_st)
        new_params, new_tx = self.opt(params, opt_state, synced)
        return (new_params, new_tx), loss


def build_lm_segments(trainer: Any) -> LMSegments:
    return LMSegments(trainer)


# ---------------------------------------------------------------------------
# The profiler
# ---------------------------------------------------------------------------


def _aot(seg: Any, *args: Any):
    """Lower+compile a jitted segment ONCE; the compiled object serves
    both the timed executions and the cost analysis (no double compile)."""
    compiled = seg.lower(*args).compile()
    return compiled, compiled_costs(compiled)


def _sub(a: float | None, b: float | None) -> float | None:
    if a is None or b is None:
        return None
    return max(0.0, a - b)


def _phase_stat(
    name: str,
    prof: DeviceProfile,
    costs: dict[str, float | None],
    device_kind: str,
    *,
    comm_bytes: float = 0.0,
) -> PhaseStat:
    ms = prof.best_ms()
    mfu = None
    peak = _flops.peak_flops_per_chip(device_kind)
    if peak and costs["flops"] and ms > 0:
        mfu = costs["flops"] / (ms / 1e3) / peak
    return PhaseStat(
        name=name,
        device_ms=prof.device_ms,
        wall_ms=prof.wall_ms,
        clock=prof.clock,
        flops=costs["flops"],
        bytes_accessed=costs["bytes_accessed"],
        comm_bytes=comm_bytes,
        mfu=mfu,
        roofline=roofline_classify(
            costs["flops"],
            costs["bytes_accessed"],
            device_kind,
            comm_bytes=comm_bytes,
        ),
    )


def _derived_backward(
    grads_prof: DeviceProfile,
    fwd_prof: DeviceProfile,
    grads_costs: dict[str, float | None],
    fwd_costs: dict[str, float | None],
    device_kind: str,
) -> PhaseStat:
    """backward = (fwd+bwd) - fwd, per clock and per cost counter."""
    device_ms = max(0.0, grads_prof.device_ms - fwd_prof.device_ms)
    wall_ms = max(0.0, grads_prof.wall_ms - fwd_prof.wall_ms)
    costs = {
        "flops": _sub(grads_costs["flops"], fwd_costs["flops"]),
        "bytes_accessed": _sub(
            grads_costs["bytes_accessed"], fwd_costs["bytes_accessed"]
        ),
    }
    prof = DeviceProfile(
        device_ms=device_ms,
        wall_ms=wall_ms,
        op_rows=[],
        iters=grads_prof.iters,
    )
    return _phase_stat("backward", prof, costs, device_kind)


def _assemble_report(
    *,
    fwd,
    grads,
    sync,
    opt,
    fused,
    comm_bytes: float,
    parity_ok: bool,
    loss_fused: float,
    loss_segmented: float,
    max_param_abs_diff: float,
    n_chips: int,
    device_kind: str,
    batch: int | None,
    iters: int,
) -> PhaseReport:
    """(prof, costs) pairs per segment -> the PhaseReport."""
    fwd_prof, fwd_costs = fwd
    grads_prof, grads_costs = grads
    sync_prof, sync_costs = sync
    opt_prof, opt_costs = opt
    fused_prof = fused
    phases = [
        _phase_stat("forward", fwd_prof, fwd_costs, device_kind),
        _derived_backward(
            grads_prof, fwd_prof, grads_costs, fwd_costs, device_kind
        ),
        _phase_stat(
            "grad_sync",
            sync_prof,
            sync_costs,
            device_kind,
            comm_bytes=comm_bytes,
        ),
        _phase_stat("optimizer", opt_prof, opt_costs, device_kind),
    ]
    fused_ms = fused_prof.best_ms()
    segmented_total = (
        grads_prof.best_ms() + sync_prof.best_ms() + opt_prof.best_ms()
    )
    # Sync time the fused step's scheduler did NOT hide: what the fused
    # step costs beyond its comm-free work (fwd+bwd + opt). The isolated
    # sync-segment time bounds it from above on a quiet machine.
    sync_exposed = max(
        0.0, fused_ms - (grads_prof.best_ms() + opt_prof.best_ms())
    )
    return PhaseReport(
        phases=phases,
        fused_ms=fused_ms,
        fused_clock=fused_prof.clock,
        segmented_total_ms=segmented_total,
        sync_exposed_ms=sync_exposed,
        parity_ok=parity_ok,
        loss_fused=loss_fused,
        loss_segmented=loss_segmented,
        max_param_abs_diff=max_param_abs_diff,
        n_chips=n_chips,
        device_kind=device_kind,
        batch=batch,
        iters=iters,
    )


def profile_phases(
    trainer: Any,
    state: Any,
    x: Any,
    y: Any,
    key: Any,
    *,
    iters: int = 3,
    top: int = 10,
) -> PhaseReport:
    """Segment, parity-check, and time one CIFAR train step.

    ``state`` is never donated (all segment programs and the fused
    clone compile without donation), so the caller's state remains
    valid. The parity check runs first on the same inputs the timed
    iterations use."""
    import jax

    from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
        sync_wire_bytes,
    )

    segs = build_cifar_segments(trainer)
    cfg = trainer.cfg
    rtol, atol, loss_rtol = _parity_tols(cfg.compute_dtype)

    new_f, m_f = segs.fused(state, x, y, key)
    new_s, loss_s = segs.segmented_step(state, x, y, key)
    loss_fused = float(m_f["loss"])
    loss_segmented = float(loss_s)
    parity_ok, max_diff = _check_parity(
        loss_fused,
        loss_segmented,
        new_f.params,
        new_s.params,
        rtol=rtol,
        atol=atol,
        loss_rtol=loss_rtol,
    )

    # Same strategy resolution the segments use, so the bytes describe
    # the sync program actually timed.
    sync_name = "allreduce" if cfg.sync in ("auto", "none") else cfg.sync
    comm_bytes = float(
        sync_wire_bytes(
            state.params,
            sync_name,
            trainer.axis_size,
            cfg.grad_compress,
            bucket_bytes=trainer._bucket_bytes,
            overlap=segs.overlap,
        )
    )
    device_kind = jax.devices()[0].device_kind
    n_chips = int(trainer.mesh.devices.size)

    fwd_c, fwd_costs = _aot(segs.forward, state, x, y, key)
    grads_c, grads_costs = _aot(segs.grads, state, x, y, key)
    loss0, g_st, stats = grads_c(state, x, y, key)
    if segs.compress:
        sync_c, sync_costs = _aot(segs.sync, g_st, state.ef)
        synced, ef = sync_c(g_st, state.ef)
        sync_args = (g_st, state.ef)
    else:
        sync_c, sync_costs = _aot(segs.sync, g_st)
        synced = sync_c(g_st)
        ef = state.ef
        sync_args = (g_st,)
    opt_c, opt_costs = _aot(segs.opt, state, synced, stats, ef)

    cap = lambda fn, *a: capture_device_profile(fn, *a, iters=iters, top=top)
    return _assemble_report(
        fwd=(cap(fwd_c, state, x, y, key), fwd_costs),
        grads=(cap(grads_c, state, x, y, key), grads_costs),
        sync=(cap(sync_c, *sync_args), sync_costs),
        opt=(cap(opt_c, state, synced, stats, ef), opt_costs),
        fused=cap(segs.fused, state, x, y, key),
        comm_bytes=comm_bytes,
        parity_ok=parity_ok,
        loss_fused=loss_fused,
        loss_segmented=loss_segmented,
        max_param_abs_diff=max_diff,
        n_chips=n_chips,
        device_kind=device_kind,
        batch=cfg.global_batch_size,
        iters=iters,
    )


def profile_lm_phases(
    trainer: Any,
    params: Any,
    opt_state: Any,
    x: Any,
    y: Any,
    *,
    iters: int = 3,
    top: int = 10,
) -> PhaseReport:
    """LM counterpart of :func:`profile_phases` (pure-DP layouts)."""
    import jax
    import jax.numpy as jnp

    from cs744_pytorch_distributed_tutorial_tpu.parallel.sync import (
        sync_wire_bytes,
    )

    segs = build_lm_segments(trainer)
    cfg = trainer.cfg
    rtol, atol, loss_rtol = _parity_tols(cfg.compute_dtype)
    with jax.transfer_guard("allow"):
        step = jnp.int32(0)

    new_p, _new_o, m_f = segs.fused(params, opt_state, x, y, step)
    (p_s, _o_s), loss_s = segs.segmented_step(params, opt_state, x, y, step)
    loss_fused = float(m_f["loss"])
    loss_segmented = float(loss_s)
    parity_ok, max_diff = _check_parity(
        loss_fused,
        loss_segmented,
        new_p,
        p_s,
        rtol=rtol,
        atol=atol,
        loss_rtol=loss_rtol,
    )

    dp_strategy = "int8_allreduce" if segs.compress else "allreduce"
    comm_bytes = float(
        sync_wire_bytes(
            params,
            dp_strategy,
            trainer.data_size,
            bucket_bytes=trainer._bucket_bytes,
            overlap=segs.overlap,
        )
    )
    device_kind = jax.devices()[0].device_kind
    n_chips = int(trainer.mesh.devices.size)

    fwd_c, fwd_costs = _aot(segs.forward, params, x, y, step)
    grads_c, grads_costs = _aot(segs.grads, params, x, y, step)
    loss0, g_st = grads_c(params, x, y, step)
    if segs.compress:
        tx_state, ef = opt_state
        sync_c, sync_costs = _aot(segs.sync, g_st, ef)
        synced, _new_ef = sync_c(g_st, ef)
        sync_args = (g_st, ef)
    else:
        tx_state = opt_state
        sync_c, sync_costs = _aot(segs.sync, g_st)
        synced = sync_c(g_st)
        sync_args = (g_st,)
    opt_c, opt_costs = _aot(segs.opt, params, tx_state, synced)

    cap = lambda fn, *a: capture_device_profile(fn, *a, iters=iters, top=top)
    return _assemble_report(
        fwd=(cap(fwd_c, params, x, y, step), fwd_costs),
        grads=(cap(grads_c, params, x, y, step), grads_costs),
        sync=(cap(sync_c, *sync_args), sync_costs),
        opt=(cap(opt_c, params, tx_state, synced), opt_costs),
        fused=cap(segs.fused, params, opt_state, x, y, step),
        comm_bytes=comm_bytes,
        parity_ok=parity_ok,
        loss_fused=loss_fused,
        loss_segmented=loss_segmented,
        max_param_abs_diff=max_diff,
        n_chips=n_chips,
        device_kind=device_kind,
        batch=cfg.global_batch_size,
        iters=iters,
    )
