"""System-level telemetry: HBM snapshots and compile-event counting.

``device.memory_stats()`` is the only portable window into HBM
pressure on TPU; it returns ``None`` on CPU (and some backends omit
individual keys), so every read here is guarded — a system record
with null memory fields is still a record of *when* we looked.

Compile counting hooks ``jax._src.monitoring``: the plain
``/jax/compilation_cache/...`` events fire once per cache *lookup*
(i.e. every jit call-site miss in the python cache), so we count the
duration event ``backend_compile_duration`` instead — it fires exactly
once per real XLA backend compile, which is the thing that silently
eats minutes when a shape leaks into a retrace loop.
"""

from __future__ import annotations

import threading
from typing import Any

__all__ = ["CompileCounter", "SystemMonitor", "hbm_stats"]

_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_lock = threading.Lock()
_compile_count = 0
_compile_secs = 0.0
_listener_installed = False


def _on_duration_event(name: str, secs: float, **_kw: Any) -> None:
    global _compile_count, _compile_secs
    if name.endswith(_COMPILE_EVENT_SUFFIX):
        with _lock:
            _compile_count += 1
            _compile_secs += float(secs)


def _ensure_listener() -> None:
    """Install the module-wide monitoring listener once. jax offers no
    unregister, so a single process-lifetime listener feeding a global
    counter is the leak-free shape; consumers snapshot deltas."""
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        _listener_installed = True  # even on failure: never retry-spam
    try:
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(_on_duration_event)
    except Exception:
        pass  # private API moved/absent: compile counts stay at zero


def _compile_totals() -> tuple[int, float]:
    with _lock:
        return _compile_count, _compile_secs


class CompileCounter:
    """Counts *backend* compiles (and seconds spent in them) observed
    since this counter was constructed."""

    def __init__(self) -> None:
        _ensure_listener()
        self._base_count, self._base_secs = _compile_totals()

    @property
    def count(self) -> int:
        return _compile_totals()[0] - self._base_count

    @property
    def seconds(self) -> float:
        return _compile_totals()[1] - self._base_secs


def hbm_stats(device: Any) -> dict[str, int] | None:
    """``device.memory_stats()`` with every failure mode flattened to
    None (CPU returns None; some backends raise)."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {str(k): int(v) for k, v in stats.items() if isinstance(v, int)}


class SystemMonitor:
    """Produces flat "system" records: worst-case HBM across local
    devices plus the compile counters. One instance per run."""

    def __init__(self) -> None:
        self.compiles = CompileCounter()

    def snapshot(self) -> dict[str, Any]:
        import jax

        record: dict[str, Any] = {
            "compile_count": self.compiles.count,
            "compile_secs": round(self.compiles.seconds, 6),
        }
        try:
            devices = jax.local_devices()
        except RuntimeError:
            devices = []
        record["local_device_count"] = len(devices)
        if devices:
            record["device_kind"] = devices[0].device_kind
        bytes_in_use: int | None = None
        peak_bytes: int | None = None
        bytes_limit: int | None = None
        for d in devices:
            stats = hbm_stats(d)
            if not stats:
                continue
            if "bytes_in_use" in stats:
                bytes_in_use = max(bytes_in_use or 0, stats["bytes_in_use"])
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                peak_bytes = max(peak_bytes or 0, peak)
            limit = stats.get("bytes_limit")
            if limit is not None:
                bytes_limit = max(bytes_limit or 0, limit)
        record["hbm_bytes_in_use"] = bytes_in_use
        record["hbm_peak_bytes_in_use"] = peak_bytes
        record["hbm_bytes_limit"] = bytes_limit
        return record
