"""Single dataclass config for the whole framework.

Replaces the reference's scattered module-level constants and 3-flag
argparse (``--master-ip``/``--num-nodes``/``--rank`` at
``master/part2a/part2a.py:136-143``; ``batch_size`` at ``:20``; SGD
hyperparameters at ``:127-128``; seed 5000 at ``:89``; hardcoded ports
29501/29508 at ``part2a.py:83`` / ``part3.py:72``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

# compute_dtype values accepted by both trainers (engine.py, lm.py).
# Resolved lazily so importing config stays jax-free.
COMPUTE_DTYPES = ("float32", "bfloat16")


def resolve_dtype(name: str):
    import jax.numpy as jnp

    table = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}
    try:
        return table[name]
    except KeyError:
        raise ValueError(
            f"unknown compute_dtype {name!r}; choose from {COMPUTE_DTYPES}"
        ) from None


@dataclasses.dataclass
class TrainConfig:
    """Everything needed to reproduce a training run.

    Defaults reproduce the reference workload: VGG-11 on CIFAR-10,
    global batch 256, SGD lr=0.1 momentum=0.9 wd=1e-4, 1 epoch,
    seed 5000 (``master/part1/part1.py:17,98-101,107``).
    """

    # Model / data
    model: str = "vgg11"
    num_classes: int = 10
    image_size: int = 32
    # ResNet stem selection: None = auto (CIFAR 3x3 stem at image_size
    # <= 64, ImageNet 7x7/stride-2 + maxpool above); True/False forces.
    # Ignored by non-ResNet models.
    imagenet_stem: bool | None = None
    # SyncBN: compute BatchNorm batch statistics ACROSS data-parallel
    # replicas (one psum per BN layer). False reproduces the reference's
    # per-replica BN (DDP default; SURVEY §7 hard part b).
    sync_bn: bool = False
    # Dropout for models that support it (the ViT family); conv models
    # follow the reference and have none.
    dropout_rate: float = 0.0
    data_root: str = "./data"
    synthetic_data: bool | None = None  # None = auto (synthetic if no local CIFAR-10)
    synthetic_train_size: int = 50_000
    synthetic_test_size: int = 10_000

    # Optimization (reference: master/part1/part1.py:98-101). The
    # reference's only recipe is fixed-LR SGD(momentum); optimizer and
    # lr_schedule are capability additions resolved by
    # train/state.py::make_optimizer. Cosine schedules need total_steps
    # (the horizon); warmup_steps linearly ramps from 0 first.
    global_batch_size: int = 256
    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    epochs: int = 1
    seed: int = 5000
    optimizer: str = "sgd"  # "sgd" | "adamw" | "lion"
    lr_schedule: str = "constant"  # "constant" | "cosine" | "warmup_cosine"
    warmup_steps: int = 0
    total_steps: int | None = None  # required by cosine schedules
    # Clip the GLOBAL gradient norm (across all params, after sync) to
    # this value before the optimizer sees it; None disables. Capability
    # addition — the reference never clips.
    grad_clip_norm: float | None = None
    # Label smoothing: target distribution (1-s) one-hot + s/num_classes.
    # 0.0 reproduces the reference's plain CE (master/part1/part1.py:94).
    label_smoothing: float = 0.0
    # Train-time crop/flip augmentation (the reference's transform_train,
    # master/part1/part1.py:68-73). False trains on normalize-only inputs
    # — needed for deterministic cross-framework trajectory comparison
    # (tests/test_torch_parity.py pins the torch loss curve this way).
    augment: bool = True
    # Gradient accumulation: split each device's batch shard into this
    # many sequential microbatches (lax.scan) — one microbatch's
    # activations live at a time. BN statistics update per microbatch.
    accum_steps: int = 1

    # Parallelism
    # none|gather_scatter|p2p_star|allreduce|ring|auto|zero1|fsdp
    # |int8_allreduce|int8_ring (quantized wire formats — see grad_compress)
    sync: str = "allreduce"
    num_devices: int | None = None  # None = all visible devices
    mesh_axes: dict[str, int] | None = None  # overrides num_devices; e.g. {"data": 4}
    # Gradient compression on the sync wire (parallel/sync.py):
    # "none" ships f32; "int8" quantizes each bucket per-chunk to int8 +
    # f32 scales (~3.9x fewer gradient bytes) and carries the
    # quantization residual as per-device error feedback so compression
    # error does not bias SGD. "int8" requires sync in
    # {allreduce, ring, int8_allreduce, int8_ring}; naming an int8_*
    # sync strategy implies grad_compress="int8".
    grad_compress: str = "none"  # "none" | "int8"
    # Bucket size (MiB) for coalesced gradient sync (parallel/buckets.py):
    # allreduce/ring/zero1/fsdp issue one collective per ~this many
    # megabytes instead of one per parameter leaf (DDP's bucketing
    # reducer). 0 disables bucketing (per-leaf collectives).
    sync_bucket_mb: float = 4.0
    # Overlapped gradient sync (parallel/overlap.py, parallel/zero.py):
    # reverse-layer-order buckets whose collectives dispatch as backward
    # produces each bucket's gradients, with the optimizer applied per
    # bucket as its sync completes — DDP's reducer schedule as dataflow.
    # "bucket" overlaps the float wire: sync in {allreduce, ring} runs
    # per-bucket mean + torch-SGD apply, sync in {zero1, fsdp} runs the
    # per-bucket psum_scatter -> per-shard apply -> all_gather schedule
    # inside the sharded optimizer. "bucket+int8" overlaps the int8+EF
    # compressed wire (allreduce/ring, or zero1 where the quantization
    # chunks live on bucket boundaries; fsdp has no separate grad wire
    # to quantize). accum_steps>1 composes: only the final micro-step's
    # sync overlaps. Requires the fixed-LR SGD recipe (this engine's
    # sharded strategies already do) and no fused_optimizer.
    sync_overlap: str = "off"  # "off" | "bucket" | "bucket+int8"

    # Numerics: params/BN stats stay float32; compute dtype is the MXU knob.
    compute_dtype: str = "float32"  # "bfloat16" on real TPU runs

    # Use the Pallas fused SGD kernel (ops/fused_sgd.py) instead of the
    # optax chain; runs in interpret mode off-TPU.
    fused_optimizer: bool = False

    # Route wide stride-1 3x3 ResNet convs through the Pallas wgrad
    # kernel (ops/fused_conv.py). Off by default: in-graph measurement
    # on the v5e showed XLA's batch-minor activation layouts force
    # relayout copies around the custom call that outweigh the kernel's
    # isolated win (see benchmarks/ablate.py round-2 notes); the flag
    # exists for shapes/layouts where the kernel wins and for tests.
    fast_conv: bool = False

    # Attention implementation for the ViT family ("dense" model
    # default, or "flash" for the Pallas kernel); rejected for the conv
    # families, which have no attention.
    vit_attention: str | None = None

    # Input-pipeline prefetch depth: batches staged ahead by a background
    # thread (the DataLoader num_workers/pin_memory analog,
    # master/part1/part1.py:80-93). 0 disables.
    prefetch_depth: int = 2

    # Debug mode: stream per-replica gradient checksums to the host each
    # step and flag replica divergence (utils/debug.py — the race-detection
    # analog, SURVEY §5.2). Adds one scalar transfer per replica per step.
    debug_sync_check: bool = False

    # Logging / instrumentation (reference prints loss every 20 batches and
    # the avg per-batch time over batches 1-10: master/part1/part1.py:39-44)
    log_every: int = 20
    timing_batches: tuple[int, int] = (1, 10)  # inclusive range averaged, step 0 (compile) excluded

    # Telemetry (obs/): metrics_dir writes manifest.json + metrics.jsonl
    # (per-step loss/grad-norm/param-norm/lr/grad_sync_bytes/step-time
    # records, rank-0 on multihost). metrics_every is the emission
    # cadence in steps; 0 = piggyback on the log_every cadence, so
    # telemetry adds no host<->device fetches beyond existing logging.
    metrics_dir: str | None = None
    metrics_every: int = 0

    # Multi-host rendezvous (mirrors init_process's signature,
    # master/part2a/part2a.py:80-85; JAX derives process_id when None)
    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    # Checkpointing (capability addition — the reference has none, SURVEY §5.4)
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0  # steps; 0 = only at end when checkpoint_dir set

    # In-memory replicated snapshots (utils/memstore.py): a second,
    # faster recovery tier above the disk checkpointer — the last
    # snapshot_keep committed TrainStates as host-RAM copies, so a
    # restart after divergence/hang restores with ZERO filesystem reads.
    # snapshot_every is the cadence in steps (0 = tier disabled); the
    # same divergence-safe pending/certify discipline as disk saves.
    snapshot_every: int = 0
    snapshot_keep: int = 2

    # Failure detection (utils/failure.py — the reference's Gloo run just
    # hangs or dies, SURVEY §5.3). halt_on_nonfinite raises
    # NonFiniteLossError when a fetched loss is NaN/inf (checked at
    # logging granularity — zero extra transfers); step_timeout_s arms a
    # host-side watchdog that logs + dumps stacks if a step hangs (the
    # first executed batch is exempt: it blocks on XLA compilation, which
    # the timing window likewise excludes). hang_action picks what the
    # watchdog does after reporting: "log" (observe only), "abort"
    # (os._exit so a supervisor — the coordination service, k8s, a shell
    # loop — restarts the process; a wedged device fetch cannot be
    # unblocked from within the process), or "escalate" (graduated:
    # first expiry warns, second adds the stack/ring/flight post-mortem,
    # third aborts — transient stalls get a chance to clear before the
    # process is killed).
    halt_on_nonfinite: bool = True
    step_timeout_s: float | None = None
    hang_action: str = "log"  # "log" | "abort" | "escalate"

    # Profiler capture (utils/profiling.py — SURVEY §5.1): when
    # profile_dir is set, fit() records an XLA device trace of
    # [profile_start_step, profile_start_step + profile_num_steps) —
    # viewable in TensorBoard's profile plugin or ui.perfetto.dev.
    # Start defaults past step 0 so compilation stays out of the trace.
    profile_dir: str | None = None
    profile_start_step: int = 10
    profile_num_steps: int = 5

    def replace(self, **kw: Any) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    @property
    def per_device_batch_size(self) -> int:
        n = self.num_devices
        if n is None:  # None = all visible devices; resolve lazily
            import jax

            n = len(jax.devices())
        if self.global_batch_size % n:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} not divisible by "
                f"num_devices={n}"
            )
        return self.global_batch_size // n


# The four reference parts as config presets. Same model, same data, same
# hyperparameters, four sync mechanisms — the pedagogical gradient the
# reference builds (SURVEY §3.5). part1 is single-device batch 256
# (part1.py:17); parts 2-3 are 64/rank x 4 ranks (part2a.py:20,32).
PART_PRESETS: dict[str, dict[str, Any]] = {
    "1": dict(sync="none", num_devices=1, global_batch_size=256),
    "2a": dict(sync="gather_scatter", num_devices=4, global_batch_size=256),
    "2a_extra": dict(sync="p2p_star", num_devices=4, global_batch_size=256),
    "2b": dict(sync="allreduce", num_devices=4, global_batch_size=256),
    "3": dict(sync="auto", num_devices=4, global_batch_size=256),
}


def config_for_part(part: str, **overrides: Any) -> TrainConfig:
    """Build a config for one of the reference's parts (1, 2a, 2a_extra, 2b, 3)."""
    if part not in PART_PRESETS:
        raise ValueError(f"unknown part {part!r}; choose from {sorted(PART_PRESETS)}")
    kw = dict(PART_PRESETS[part])
    kw.update(overrides)
    return TrainConfig(**kw)
