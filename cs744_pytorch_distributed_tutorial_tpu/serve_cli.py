"""CLI for the continuous-batching serving engine (serve/).

Runs the Poisson load benchmark against a paged-KV ``ServingEngine``
and (optionally) the batch-at-a-time baseline at equal HBM budget,
emitting ``kind:"serve"`` / ``kind:"serve_summary"`` records to stdout
and ``--metrics-dir`` (docs/serving.md):

    # engine vs batch-at-a-time generate, equal KV-token budget,
    # exit 1 unless the engine wins p99 TTFT AND tokens/sec:
    python -m cs744_pytorch_distributed_tutorial_tpu.serve_cli \
        --requests 32 --rate 16 --compare-baseline --gate

    # greedy paged-vs-dense parity audit over the workload's prompts:
    python -m cs744_pytorch_distributed_tutorial_tpu.serve_cli \
        --requests 8 --parity-check

    # graftserve: Perfetto span timeline + windowed SLO records +
    # device-time attribution of the decode/prefill programs
    # (docs/observability.md; obs serve-report renders/checks it):
    python -m cs744_pytorch_distributed_tutorial_tpu.serve_cli \
        --requests 24 --trace-dir /tmp/serve_trace --window-every 0.25

    # graftguard: overload the engine 3x past sustainable, shed at the
    # door, expire stale requests, and ride out injected decode faults
    # under the supervised restart ladder (docs/reliability.md):
    python -m cs744_pytorch_distributed_tutorial_tpu.serve_cli \
        --requests 64 --rate 48 --deadline-s 30 --max-queue-depth 16 \
        --shed-policy degrade --chaos 40:decode_nan,90:engine_crash

Params are randomly initialized — serving latency/throughput and the
parity contract are weight-independent, so the CLI does not train.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cs744-tpu-serve",
        description="Continuous-batching LM serving: Poisson load benchmark",
    )
    # model (decode-configured TransformerLM, random params)
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--num-kv-heads", type=int, default=None)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--use-rope", action="store_true")
    p.add_argument("--quant-kv", action="store_true",
                   help="int8 KV pages (ops/quant.py::quantize_kv)")
    # engine geometry
    p.add_argument("--num-slots", type=int, default=8,
                   help="decode slots B in the fixed-shape jitted step")
    p.add_argument("--page-size", type=int, default=16,
                   help="tokens per KV page")
    p.add_argument("--num-pages", type=int, default=64,
                   help="pool pages per layer (page 0 reserved as trash)")
    p.add_argument("--max-pages-per-slot", type=int, default=16,
                   help="page-table width P: caps one request's KV")
    p.add_argument("--paged-attention-impl", default="auto",
                   choices=("auto", "gather", "kernel"),
                   help="decode attention: Pallas live-pages kernel or "
                        "the gather+einsum reference (auto: kernel on "
                        "TPU, gather elsewhere)")
    # sampling
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--eos-id", type=int, default=None)
    # workload
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=16.0,
                   help="Poisson arrival rate, requests/sec")
    p.add_argument("--prompt-len", type=int, nargs=2, default=(8, 48),
                   metavar=("MIN", "MAX"))
    p.add_argument("--output-len", type=int, nargs=2, default=(8, 64),
                   metavar=("MIN", "MAX"))
    p.add_argument("--seed", type=int, default=0)
    # modes
    p.add_argument("--compare-baseline", action="store_true",
                   help="also replay through batch-at-a-time generate at "
                        "EQUAL KV HBM (batch = pool tokens / max_seq_len)")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 unless the engine beats the baseline on "
                        "both aggregate tokens/sec and p99 TTFT "
                        "(implies --compare-baseline)")
    p.add_argument("--parity-check", action="store_true",
                   help="greedy engine output must match make_generator "
                        "token-for-token on every workload prompt; exit 1 "
                        "on any mismatch")
    p.add_argument("--metrics-dir", default=None,
                   help="also write records to METRICS_DIR/metrics.jsonl")
    # graftserve observability (obs/serve_trace.py, docs/observability.md)
    p.add_argument("--trace-dir", default=None,
                   help="write graftserve artifacts here: the Perfetto "
                        "trace (serve_trace.json), span/window/request "
                        "JSONL, and serve_phases.json (device-time + "
                        "roofline attribution of the decode/prefill "
                        "programs)")
    p.add_argument("--window-every", type=float, default=None, metavar="S",
                   help="emit kind:'serve_window' SLO records every S "
                        "seconds of the measured run (rolling TTFT/ITL "
                        "p50/p99, queue depth, preemption rate, pool "
                        "counters); defaults to 0.25 when --trace-dir "
                        "is set")
    # graftguard: deadlines + admission control (serve/guard.py);
    # setting any of these attaches a ServeGuard to the engine
    p.add_argument("--deadline-s", type=float, default=None,
                   help="default end-to-end deadline per request; "
                        "expiry retires it as timed_out and frees its "
                        "pages")
    p.add_argument("--max-queue-s", type=float, default=None,
                   help="max time a request may wait for its FIRST "
                        "token while queued")
    p.add_argument("--max-queue-depth", type=int, default=None,
                   help="bounded admission queue: arrivals beyond this "
                        "depth are shed at the door")
    p.add_argument("--shed-policy", default=None,
                   choices=("reject", "degrade"),
                   help="overload response: reject new arrivals, or "
                        "degrade (trim max_new_tokens to the floor "
                        "under page-pool pressure; outputs stay oracle "
                        "prefixes)")
    p.add_argument("--degrade-floor", type=int, default=8,
                   help="min max_new_tokens a degrade trim leaves")
    # chaos + supervised auto-recovery (utils/chaos.py, serve/guard.py)
    p.add_argument("--chaos", default=None, metavar="IDX:KIND,...",
                   help="inject serve faults at measured decode-step "
                        "indices, e.g. '40:decode_nan,90:engine_crash'; "
                        "kinds: decode_nan, slow_step, engine_crash. "
                        "Implies the supervised recovery loop")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="engine restarts before recovery gives up")
    p.add_argument("--restart-backoff-s", type=float, default=0.0,
                   help="base exponential-backoff delay between "
                        "restarts")
    p.add_argument("--step-timeout-s", type=float, default=None,
                   help="watchdog deadline per decode step: a hung "
                        "step escalates warn -> flight dump -> engine "
                        "restart. Implies the supervised recovery loop")
    return p


def _parse_chaos(spec: str) -> dict[int, str]:
    """``"40:decode_nan,90:engine_crash"`` -> ``{40: ..., 90: ...}``."""
    faults: dict[int, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        idx, sep, kind = part.partition(":")
        if not sep:
            raise ValueError(f"chaos spec {part!r} is not IDX:KIND")
        faults[int(idx)] = kind
    return faults


def _make_sink(metrics_dir: str | None):
    from cs744_pytorch_distributed_tutorial_tpu.obs.sinks import (
        JsonlSink,
        MultiSink,
        StreamSink,
    )

    sinks = [StreamSink(sys.stdout)]
    if metrics_dir:
        import os

        os.makedirs(metrics_dir, exist_ok=True)
        sinks.append(JsonlSink(os.path.join(metrics_dir, "metrics.jsonl")))
    return MultiSink(sinks)


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cs744_pytorch_distributed_tutorial_tpu.models.transformer import (
        TransformerLM,
    )
    from cs744_pytorch_distributed_tutorial_tpu.serve import (
        GuardConfig,
        Request,
        ServeConfig,
        ServeGuard,
        ServingEngine,
        make_poisson_workload,
        run_batch_baseline,
        run_poisson,
        run_serve_with_recovery,
    )

    model = TransformerLM(
        vocab_size=args.vocab_size,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        num_kv_heads=args.num_kv_heads,
        d_model=args.d_model,
        d_ff=args.d_ff,
        max_seq_len=args.max_seq_len,
        attention_impl="dense",
        use_rope=args.use_rope,
        quant_kv_cache=args.quant_kv,
    )
    params = model.init(
        jax.random.key(args.seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    cfg = ServeConfig(
        num_slots=args.num_slots,
        page_size=args.page_size,
        num_pages=args.num_pages,
        max_pages_per_slot=args.max_pages_per_slot,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        eos_id=args.eos_id,
        seed=args.seed,
        paged_attention_impl=args.paged_attention_impl,
    )
    workload = make_poisson_workload(
        num_requests=args.requests,
        rate_rps=args.rate,
        prompt_len=tuple(args.prompt_len),
        output_len=tuple(args.output_len),
        vocab_size=args.vocab_size,
        seed=args.seed,
    )
    sink = _make_sink(args.metrics_dir)
    failed = False
    try:
        if args.parity_check:
            from cs744_pytorch_distributed_tutorial_tpu.infer import (
                make_generator,
            )

            engine = ServingEngine(model, params, cfg, sink=None)
            for i, prompt in enumerate(workload.prompts):
                engine.submit(Request(
                    prompt=prompt,
                    max_new_tokens=int(workload.max_new_tokens[i]),
                ))
            by_id = {r.req_id: r for r in engine.run()}
            gens: dict[int, object] = {}
            mismatches = 0
            for i, prompt in enumerate(workload.prompts):
                n = int(workload.max_new_tokens[i])
                if n not in gens:
                    gens[n] = make_generator(
                        model, max_new_tokens=n, temperature=0.0,
                        eos_id=cfg.eos_id,
                    )
                ref = np.asarray(
                    gens[n](params, prompt[None, :], jax.random.key(0))
                )[0].tolist()
                if cfg.eos_id is not None and cfg.eos_id in ref:
                    ref = ref[: ref.index(cfg.eos_id) + 1]
                if by_id[i].generated != ref:
                    mismatches += 1
            sink.emit({
                "kind": "serve",
                "event": "parity",
                "requests": len(workload),
                "mismatches": mismatches,
                "parity_ok": mismatches == 0,
            })
            failed |= mismatches > 0

        tracer = None
        window_every = args.window_every
        if args.trace_dir and window_every is None:
            window_every = 0.25
        if args.trace_dir or window_every is not None:
            from cs744_pytorch_distributed_tutorial_tpu.obs.serve_trace import (
                ServeTracer,
            )

            tracer = ServeTracer(
                args.num_slots, window_every_s=window_every
            )
        guard = None
        if any(v is not None for v in (
            args.deadline_s, args.max_queue_s,
            args.max_queue_depth, args.shed_policy,
        )):
            guard = ServeGuard(cfg=GuardConfig(
                deadline_s=args.deadline_s,
                max_queue_s=args.max_queue_s,
                max_queue_depth=args.max_queue_depth,
                shed_policy=args.shed_policy or "reject",
                degrade_floor=args.degrade_floor,
            ))

        if args.chaos or args.step_timeout_s is not None:
            # Supervised recovery loop: the supervisor owns the flight
            # recorder (one per engine generation, armed by its step
            # watchdog) and restarts the engine from its snapshot on
            # any ServeFailure.
            from cs744_pytorch_distributed_tutorial_tpu.utils.chaos import (
                SERVE_FAULT_KINDS,
                FaultSchedule,
                ServeChaosMonkey,
            )

            monkey = None
            if args.chaos:
                faults = _parse_chaos(args.chaos)
                bad = sorted(
                    set(faults.values()) - set(SERVE_FAULT_KINDS)
                )
                if bad:
                    raise SystemExit(
                        f"--chaos kinds {bad} not in {SERVE_FAULT_KINDS}"
                    )
                monkey = ServeChaosMonkey(
                    FaultSchedule(faults), telemetry=sink
                )

            engines: list = []

            def make_engine():
                eng = ServingEngine(
                    model, params, cfg,
                    sink=sink, tracer=tracer, guard=guard,
                )
                engines.append(eng)
                return eng

            serve_rec = run_serve_with_recovery(
                make_engine, workload,
                monkey=monkey,
                max_restarts=args.max_restarts,
                backoff_s=args.restart_backoff_s,
                step_timeout_s=args.step_timeout_s,
                telemetry=sink,
                sink=sink,
            )
            engine = engines[-1]
        else:
            engine = ServingEngine(
                model, params, cfg, sink=sink, tracer=tracer, guard=guard,
            )
            # Flight recorder over the serving loop: SIGTERM/uncaught-
            # crash dumps the serve event ring tail + pool high-water
            # through the sink — same discipline the training engines
            # get.
            flight = engine.make_flight_recorder()
            flight.install()
            try:
                serve_rec = run_poisson(engine, workload, sink=sink)
            finally:
                flight.uninstall()

        if args.trace_dir:
            import os

            from cs744_pytorch_distributed_tutorial_tpu.obs.serve_trace import (
                profile_serve_programs,
            )

            tracer.write(args.trace_dir)
            # Post-run on purpose: profiling re-runs + AOT-compiles the
            # programs, which must stay outside the measured (0-retrace)
            # section.
            phase_recs = profile_serve_programs(engine)
            for rec in phase_recs:
                sink.emit(rec)
            with open(
                os.path.join(args.trace_dir, "serve_phases.json"),
                "w", encoding="utf-8",
            ) as f:
                json.dump(phase_recs, f, indent=1)

        if args.compare_baseline or args.gate:
            pool_tokens = cfg.num_pages * cfg.page_size
            batch = max(1, pool_tokens // args.max_seq_len)
            base_rec = run_batch_baseline(
                model, params, workload,
                batch_size=batch,
                temperature=args.temperature,
                eos_id=args.eos_id,
                sink=sink,
            )
            comparison = {
                "kind": "serve",
                "event": "comparison",
                "baseline_batch": batch,
                "engine_kv_tokens": pool_tokens,
                "baseline_kv_tokens": batch * args.max_seq_len,
                "tokens_per_sec_ratio": round(
                    serve_rec["tokens_per_sec"]
                    / max(1e-9, base_rec["tokens_per_sec"]), 3
                ),
                "ttft_p99_ratio": round(
                    serve_rec["ttft_p99_ms"]
                    / max(1e-9, base_rec["ttft_p99_ms"]), 3
                ),
                "engine_wins": (
                    serve_rec["tokens_per_sec"] > base_rec["tokens_per_sec"]
                    and serve_rec["ttft_p99_ms"] < base_rec["ttft_p99_ms"]
                ),
            }
            sink.emit(comparison)
            if args.gate and not comparison["engine_wins"]:
                print(
                    json.dumps({
                        "gate": "serve",
                        "error": "continuous batching did not beat the "
                                 "batch-at-a-time baseline on both "
                                 "tokens/sec and p99 TTFT",
                    }),
                    file=sys.stderr,
                )
                failed = True
    finally:
        sink.close()
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
