"""Native (C++) runtime components, loaded via ctypes.

The reference's native surface is all in its dependencies — libtorch
ATen, Gloo, torchvision C extensions, DataLoader worker processes
(SURVEY §2.2). The compute path here is XLA/Pallas; this package holds
the host-side runtime pieces that warrant native code, compiled on first
use with the baked-in g++ (no pybind11 in the image; bindings are
ctypes over an ``extern "C"`` surface). Every consumer has a pure-NumPy
fallback, so the framework works even where no compiler exists.
"""

from cs744_pytorch_distributed_tutorial_tpu.native.build import (
    load_library,
    native_available,
)

__all__ = ["load_library", "native_available"]
