"""Compile-on-first-use for the native components.

g++ is baked into the image but pip installs are not allowed, so the
shared library is built directly (``g++ -O3 -shared -fPIC``) into a
version-keyed cache next to this package the first time it's needed.
Failures degrade gracefully: consumers check ``native_available()`` and
fall back to NumPy.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, object] = {}


def _source_path(name: str) -> str:
    return os.path.join(_DIR, f"{name}.cpp")


def _lib_path(name: str) -> str:
    # Key the artifact to the source hash so edits trigger rebuilds and
    # stale .so files are never loaded.
    with open(_source_path(name), "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_DIR, f"_{name}_{digest}.so")


def _build(name: str) -> str | None:
    src, lib = _source_path(name), _lib_path(name)
    if os.path.exists(lib):
        return lib
    # Per-process scratch name: concurrent builders (multi-host shared
    # filesystems, pytest-xdist) must not write the same tmp file, or a
    # half-written .so could be os.replace()d into the digest-keyed path
    # and cached as corrupt forever. os.replace itself is atomic.
    tmp = f"{lib}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        src, "-o", tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(tmp, lib)
        return lib
    except (subprocess.SubprocessError, OSError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load_library(name: str = "batcher"):
    """ctypes.CDLL for a native component, or None if unbuildable."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        lib_path = _build(name)
        lib = None
        if lib_path is not None:
            import ctypes

            try:
                lib = ctypes.CDLL(lib_path)
            except OSError:
                lib = None
        _CACHE[name] = lib
        return lib


def native_available(name: str = "batcher") -> bool:
    return load_library(name) is not None
