// Native batch-assembly core: the multiprocess-DataLoader-worker analog.
//
// The reference's input pipeline leans on torchvision/libtorch native code:
// DataLoader with num_workers=2 worker processes and pinned staging buffers
// (master/part1/part1.py:80-93). Its hot host-side op — assembling a batch
// by gathering N example records into one contiguous buffer — happens in
// torch's C++ collate path. This is the TPU framework's equivalent: a
// small C++ core doing the memcpy-bound index-gather with a thread pool,
// called from Python via ctypes (no pybind11 in this image), feeding
// buffers that jax.device_put ships to the chip.
//
// Layout contract: `images` is a C-contiguous [num_examples, item_bytes]
// uint8 array; `indices` int64; `out` [num_indices, item_bytes]. The
// gather is pure memcpy so threads partition the index range with no
// shared writes.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Gather rows of a uint8 matrix: out[i] = images[indices[i]].
// Returns 0 on success, -1 on a bad argument (null pointer or index out
// of range — checked up front so worker threads never fault).
int gather_u8(const uint8_t* images,
              int64_t num_examples,
              int64_t item_bytes,
              const int64_t* indices,
              int64_t num_indices,
              uint8_t* out,
              int num_threads) {
  if (!images || !indices || !out || item_bytes <= 0 || num_indices < 0) {
    return -1;
  }
  for (int64_t i = 0; i < num_indices; ++i) {
    if (indices[i] < 0 || indices[i] >= num_examples) return -1;
  }
  if (num_threads < 1) num_threads = 1;
  const int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  num_threads = static_cast<int>(
      std::min<int64_t>(num_threads, std::max<int64_t>(hw, 1)));
  // Below ~1 MiB of payload the thread spawn overhead dominates.
  if (num_indices * item_bytes < (1 << 20)) num_threads = 1;

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(out + i * item_bytes,
                  images + indices[i] * item_bytes,
                  static_cast<size_t>(item_bytes));
    }
  };
  if (num_threads == 1) {
    worker(0, num_indices);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(num_threads);
  const int64_t chunk = (num_indices + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min<int64_t>(lo + chunk, num_indices);
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Same contract for int32 rows (labels gathered alongside images).
int gather_i32(const int32_t* src,
               int64_t num_examples,
               int64_t row_elems,
               const int64_t* indices,
               int64_t num_indices,
               int32_t* out,
               int num_threads) {
  return gather_u8(reinterpret_cast<const uint8_t*>(src), num_examples,
                   row_elems * static_cast<int64_t>(sizeof(int32_t)), indices,
                   num_indices, reinterpret_cast<uint8_t*>(out), num_threads);
}

}  // extern "C"
