// Native CIFAR-10 binary-format decoder: the torchvision-C-extension
// analog for the dataset's official binary distribution
// (cifar-10-binary.tar.gz). Each record is 3073 bytes: 1 label byte
// followed by a 3x32x32 CHW pixel plane. Decoding = split labels out and
// transpose CHW -> HWC (the TPU conv layout) — a pure memory permutation,
// threaded over records.
//
// The reference reads the *pickle* distribution through torchvision's
// Python/C stack (master/part1/part1.py:78-79); data/cifar10.py reads
// that format in Python and routes the binary format here.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {
constexpr int64_t kH = 32, kW = 32, kC = 3;
constexpr int64_t kPlane = kH * kW;          // 1024
constexpr int64_t kRecord = 1 + kC * kPlane; // 3073
}  // namespace

extern "C" {

// records: [n * 3073] bytes; labels_out: [n] int32; images_out:
// [n, 32, 32, 3] uint8 (C-contiguous). Returns 0 on success, -1 on bad
// arguments.
int decode_cifar_u8(const uint8_t* records,
                    int64_t n,
                    int32_t* labels_out,
                    uint8_t* images_out,
                    int num_threads) {
  if (!records || !labels_out || !images_out || n < 0) return -1;
  if (num_threads < 1) num_threads = 1;
  const int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  num_threads = static_cast<int>(
      std::min<int64_t>(num_threads, std::max<int64_t>(hw, 1)));
  if (n * kRecord < (1 << 20)) num_threads = 1;  // spawn overhead floor

  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* rec = records + i * kRecord;
      labels_out[i] = static_cast<int32_t>(rec[0]);
      const uint8_t* r = rec + 1;
      const uint8_t* g = r + kPlane;
      const uint8_t* b = g + kPlane;
      uint8_t* out = images_out + i * kC * kPlane;
      for (int64_t p = 0; p < kPlane; ++p) {
        out[p * kC + 0] = r[p];
        out[p * kC + 1] = g[p];
        out[p * kC + 2] = b[p];
      }
    }
  };
  if (num_threads == 1) {
    worker(0, n);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(num_threads);
  const int64_t chunk = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
