"""Sharded sampling: the ``DistributedSampler`` contract, reimplemented.

The reference shards the training set per rank with
``torch.utils.data.DistributedSampler`` (``master/part2a/part2a.py:107``):
a (seed, epoch)-deterministic global permutation, wrap-around padding to a
multiple of the world size, then a strided rank split. Same contract here,
generalized to any shard count (the reference hardcodes world size 4
everywhere — SURVEY §2.3).
"""

from __future__ import annotations

import numpy as np


def epoch_permutation(
    num_examples: int, seed: int, epoch: int, shuffle: bool
) -> np.ndarray:
    """(seed, epoch)-deterministic example order — every process computes
    the identical plan with no communication (the DistributedSampler
    ``set_epoch`` discipline, ``master/part2a/part2a.py:89-90,107``)."""
    if shuffle:
        return np.random.default_rng((seed, epoch)).permutation(num_examples)
    return np.arange(num_examples)


def wrap_pad(order: np.ndarray, total: int) -> np.ndarray:
    """Truncate or cyclically repeat ``order`` to exactly ``total`` entries
    (DistributedSampler's wrap-around padding, repeating as many times as
    needed when ``total`` exceeds the dataset size)."""
    if total <= len(order):
        return order[:total]
    return np.resize(order, total)


class ShardedSampler:
    """Deterministic equal-size sharding of ``range(num_examples)``.

    Guarantees (the DistributedSampler contract):
    - every shard has the same length: ``ceil(n / num_shards)`` with
      wrap-around padding, or ``floor(n / num_shards)`` with ``drop_last``;
    - the union of all shards covers the dataset (padding duplicates at
      most ``num_shards - 1`` examples);
    - ``indices(epoch)`` is a pure function of
      ``(seed, epoch, shard, num_shards)`` — every process computes its own
      shard with no communication;
    - ``shuffle=False`` gives the plain strided split
      ``[shard, shard + num_shards, ...]``.
    """

    def __init__(
        self,
        num_examples: int,
        num_shards: int,
        shard: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        drop_last: bool = False,
    ):
        if not 0 <= shard < num_shards:
            raise ValueError(f"shard {shard} out of range for {num_shards} shards")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard = shard
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        if drop_last:
            self._per_shard = num_examples // num_shards
        else:
            self._per_shard = -(-num_examples // num_shards)  # ceil

    def __len__(self) -> int:
        return self._per_shard

    def indices(self, epoch: int) -> np.ndarray:
        """This shard's example indices for ``epoch``."""
        order = epoch_permutation(self.num_examples, self.seed, epoch, self.shuffle)
        order = wrap_pad(order, self._per_shard * self.num_shards)
        return order[self.shard :: self.num_shards]
