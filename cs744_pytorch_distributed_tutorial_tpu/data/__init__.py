"""Data pipeline: CIFAR-10 source, sharded sampling, global-batch loading,
on-device augmentation — the torchvision + DataLoader + DistributedSampler
stack (``master/part1/part1.py:66-93``, ``master/part2a/part2a.py:103-113``)
rebuilt TPU-first (host ships uint8; transforms trace into the jitted step)."""

from cs744_pytorch_distributed_tutorial_tpu.data.augment import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    augment_train_batch,
    eval_batch,
    normalize,
    random_crop_flip,
)
from cs744_pytorch_distributed_tutorial_tpu.data.cifar10 import (
    CIFAR10Dataset,
    load_cifar10,
    synthetic_cifar10,
    synthetic_images,
)
from cs744_pytorch_distributed_tutorial_tpu.data.loader import BatchLoader
from cs744_pytorch_distributed_tutorial_tpu.data.native_batcher import gather_rows
from cs744_pytorch_distributed_tutorial_tpu.data.prefetch import (
    PrefetchIterator,
    prefetch,
)
from cs744_pytorch_distributed_tutorial_tpu.data.sampler import ShardedSampler
from cs744_pytorch_distributed_tutorial_tpu.data.text import (
    BYTE_VOCAB,
    byte_corpus,
    synthetic_tokens,
)

__all__ = [
    "CIFAR10_MEAN",
    "CIFAR10_STD",
    "CIFAR10Dataset",
    "BatchLoader",
    "ShardedSampler",
    "augment_train_batch",
    "eval_batch",
    "normalize",
    "random_crop_flip",
    "gather_rows",
    "load_cifar10",
    "prefetch",
    "PrefetchIterator",
    "BYTE_VOCAB",
    "byte_corpus",
    "synthetic_cifar10",
    "synthetic_images",
    "synthetic_tokens",
]
