"""Background prefetch: overlap host batch assembly with device compute.

The reference overlaps input work with training via DataLoader worker
processes and pinned staging memory (``num_workers=2, pin_memory=True``,
``master/part1/part1.py:80-93``). The TPU-native shape of the same idea:
a producer thread runs the loader (index plan -> native gather ->
``device_put`` into the sharded layout) ``depth`` batches ahead, so the
host stages batch N+1 while the chip runs batch N. JAX dispatch is
already async on the compute side; the thread covers the host-side
assembly+transfer latency that would otherwise serialize with it.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_STOP = object()


def _trace_annotation(name: str):
    """Profiler annotation for the producer thread, so Perfetto captures
    show host batch assembly as labeled spans on the prefetch lane (the
    graftscope label map, docs/observability.md). Null context when jax
    is absent — this module must stay importable on jax-less hosts."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover — jax-less host tooling
        import contextlib

        return contextlib.nullcontext()


class PrefetchIterator(Iterator[T]):
    """Wrap any iterator; a daemon thread keeps ``depth`` items ready.

    Exceptions in the producer re-raise at the consuming ``next()`` call.
    ``close()`` (or garbage collection of the iterator) stops the thread.
    """

    def __init__(self, iterable: Iterable[T], depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._exhausted = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iter(iterable),), daemon=True
        )
        self._thread.start()

    def _offer(self, item) -> bool:
        """Blocking put that still honors close(); True if enqueued."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, it: Iterator[T]) -> None:
        try:
            prev = None
            while True:
                # Materialize the PREVIOUS item on the producer thread
                # before pulling the next: the consumer never absorbs
                # deferred device_put work inside its own dispatch
                # chain, while THIS item's transfer still overlaps the
                # next batch's host assembly (blocking on the fresh item
                # itself would serialize gather with transfer — the
                # overlap this thread exists for). One-behind is enough:
                # by the time the consumer dequeues an item, its
                # successor's production has fenced it. Transfer errors
                # surface here and relay to the consumer like any other
                # producer exception.
                with _trace_annotation("graftscope/prefetch_produce"):
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    self._block_ready(prev)
                prev = item
                if not self._offer(item):
                    return
            self._block_ready(prev)
            self._offer(_STOP)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            self._offer(e)
            # Terminate the stream for consumers that keep reading after
            # catching the relayed exception (a further next() would
            # otherwise block forever on the empty queue).
            self._offer(_STOP)

    @staticmethod
    def _block_ready(item) -> None:
        if item is None:
            return
        try:
            import jax
        except Exception:  # pragma: no cover — jax-less host tooling
            return
        # Non-array leaves pass through untouched (block_until_ready
        # ignores them); DEVICE errors deliberately propagate — the
        # producer's relay is exactly where they belong.
        jax.block_until_ready(item)

    def __iter__(self) -> "PrefetchIterator[T]":
        return self

    def __next__(self) -> T:
        if self._exhausted:
            # StopIteration must PERSIST (iterator protocol): the queue
            # holds a single _STOP sentinel, so without this flag a
            # retrying consumer's second next() would block forever on
            # the empty queue.
            raise StopIteration
        item = self._q.get()
        if item is _STOP:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        self._stop.set()

    def __del__(self) -> None:  # pragma: no cover - GC timing
        self.close()


def prefetch(iterable: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Functional spelling: ``for batch in prefetch(loader.epoch(e)):``."""
    if depth == 0:
        return iter(iterable)
    return PrefetchIterator(iterable, depth)
