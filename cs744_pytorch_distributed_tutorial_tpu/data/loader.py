"""Batch loading: global batches as data-sharded ``jax.Array``s.

Replaces the reference's ``DataLoader(num_workers=2, pin_memory=True)`` +
``DistributedSampler`` pair (``master/part1/part1.py:80-93``,
``master/part2a/part2a.py:103-113``). Where torch runs worker processes
per rank yielding rank-local tensors, here each epoch is a deterministic
index plan (``sampler.epoch_permutation`` + ``wrap_pad`` — the same
primitives ``ShardedSampler`` is built from) and every batch is ONE global
``jax.Array`` laid out along the mesh's data axis — single-host via
``device_put`` with a ``NamedSharding``, multi-host via
``jax.make_array_from_process_local_data`` where each process contributes
only the shard it will feed its local devices.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.data.native_batcher import gather_rows
from cs744_pytorch_distributed_tutorial_tpu.data.sampler import (
    epoch_permutation,
    wrap_pad,
)
from cs744_pytorch_distributed_tutorial_tpu.parallel.mesh import (
    DATA_AXIS,
    local_to_global_batch,
    shard_global_batch,
)


class BatchLoader:
    """Deterministic sharded batch iterator over in-memory arrays.

    ``epoch(e)`` yields ``(images, labels)`` global arrays of exactly
    ``global_batch_size``, wrap-around padding the final batch (the
    DistributedSampler contract) unless ``drop_last``. Shapes are static
    across all batches — one XLA compilation per run.

    ``epoch_padded(e)`` yields ``(images, labels, mask)`` where the tail
    batch is zero-padded and ``mask`` is 1.0 on real examples — so eval
    counts every example exactly once on any mesh, which is the working
    version of the reference's broken eval aggregation (the slave's
    ``isend`` of its ``correct`` count that the master never receives,
    ``slave/part2b/part2b.py:67-69``, SURVEY §2.1 #6).
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        global_batch_size: int,
        *,
        mesh: jax.sharding.Mesh,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        axis: str = DATA_AXIS,
    ):
        if len(images) != len(labels):
            raise ValueError(
                f"images/labels length mismatch: {len(images)} vs {len(labels)}"
            )
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels, dtype=np.int32)
        self.global_batch_size = int(global_batch_size)
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.axis = axis
        self.num_examples = len(images)
        if self.num_examples == 0:
            raise ValueError("empty dataset")
        if drop_last and self.num_examples < self.global_batch_size:
            raise ValueError(
                f"dataset of {self.num_examples} examples yields ZERO batches of "
                f"{self.global_batch_size} with drop_last=True; shrink the batch "
                "or pass drop_last=False (wrap-around pad)"
            )

    def __len__(self) -> int:
        """Batches per epoch."""
        if self.drop_last:
            return max(self.num_examples // self.global_batch_size, 0)
        return -(-self.num_examples // self.global_batch_size)  # ceil

    # ------------------------------------------------------------------ place
    def _put_global(self, *arrays: np.ndarray):
        """Place per-example host arrays (identical on every process) as
        global data-sharded jax.Arrays — all with the same slice math, so
        data/labels/mask can never land on mismatched layouts."""
        if jax.process_count() == 1:
            return shard_global_batch(self.mesh, *arrays, axis=self.axis)
        # Multi-host: each process materializes only its contiguous slice
        # of the global batch; consistent because every process computed
        # the identical (seed, epoch)-deterministic plan.
        n, p, i = len(arrays[0]), jax.process_count(), jax.process_index()
        lo, hi = n * i // p, n * (i + 1) // p
        return local_to_global_batch(
            self.mesh, *(a[lo:hi] for a in arrays), axis=self.axis
        )

    # ------------------------------------------------------------------ epochs
    def epoch(
        self, epoch: int, start: int = 0
    ) -> Iterator[tuple[jax.Array, jax.Array]]:
        """Full-size training batches (wrap-padded unless ``drop_last``).

        ``start`` skips the first batches of the epoch's deterministic
        plan WITHOUT assembling or transferring them — index arithmetic
        only (the mid-epoch checkpoint-resume path, train/engine.py)."""
        order = epoch_permutation(self.num_examples, self.seed, epoch, self.shuffle)
        bsz = self.global_batch_size
        order = wrap_pad(order, len(self) * bsz)
        for b in range(start, len(self)):
            idx = order[b * bsz : (b + 1) * bsz]
            yield self._put_global(
                gather_rows(self.images, idx), gather_rows(self.labels, idx)
            )

    def epoch_padded(
        self, epoch: int
    ) -> Iterator[tuple[jax.Array, jax.Array, jax.Array]]:
        """Eval batches with a validity mask; every example appears exactly
        once, shapes stay static (pad entries replay index 0, mask 0.0)."""
        order = epoch_permutation(self.num_examples, self.seed, epoch, self.shuffle)
        bsz = self.global_batch_size
        n_batches = -(-self.num_examples // bsz)  # ceil: never drop for eval
        for b in range(n_batches):
            idx = order[b * bsz : (b + 1) * bsz]
            n_real = len(idx)
            mask = np.zeros(bsz, dtype=np.float32)
            mask[:n_real] = 1.0
            if n_real < bsz:
                idx = np.concatenate([idx, np.zeros(bsz - n_real, dtype=idx.dtype)])
            yield self._put_global(
                gather_rows(self.images, idx), gather_rows(self.labels, idx), mask
            )
