"""Synthetic token streams for the LM/long-context path.

Counterpart of ``cifar10.synthetic_cifar10`` for the transformer family:
deterministic, learnable structure (each sequence follows a per-class
cyclic token pattern with noise), so LM tests can assert loss decrease
without a real corpus in this no-egress environment.
"""

from __future__ import annotations

import numpy as np


def synthetic_tokens(
    num_seqs: int,
    seq_len: int,
    vocab_size: int,
    *,
    seed: int = 0,
    noise: float = 0.05,
) -> np.ndarray:
    """[num_seqs, seq_len + 1] int32 tokens (callers split input/target).

    Each sequence walks the vocab with a fixed per-sequence stride, so the
    next token is a deterministic function of the current one — a pattern
    a causal LM learns within a few steps — with ``noise`` fraction of
    positions replaced by uniform random tokens.
    """
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, vocab_size, size=num_seqs)
    strides = rng.integers(1, max(vocab_size // 4, 2), size=num_seqs)
    pos = np.arange(seq_len + 1)
    tokens = (starts[:, None] + strides[:, None] * pos[None, :]) % vocab_size
    corrupt = rng.random(tokens.shape) < noise
    tokens = np.where(
        corrupt, rng.integers(0, vocab_size, size=tokens.shape), tokens
    )
    return tokens.astype(np.int32)


BYTE_VOCAB = 256


def byte_corpus(
    path: str,
    seq_len: int,
    *,
    stride: int | None = None,
    max_seqs: int | None = None,
    shuffle: bool = True,
    seed: int = 0,
) -> np.ndarray:
    """Byte-level tokenization of a local file -> [N, seq_len + 1] int32.

    The zero-dependency tokenizer (vocab = 256 byte values): windows of
    ``seq_len + 1`` bytes taken every ``stride`` positions (default
    non-overlapping), shuffled deterministically so ``LMTrainer.fit``'s
    sequential batch plan still sees mixed data. Pairs with
    ``LMConfig(vocab_size=256)``; decode generated ids with
    ``bytes(ids).decode(errors='replace')``.
    """
    data = np.fromfile(path, dtype=np.uint8)
    window = seq_len + 1
    if len(data) < window:
        raise ValueError(
            f"corpus {path!r} has {len(data)} bytes < seq_len + 1 = {window}"
        )
    stride = stride or window
    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")
    windows = np.lib.stride_tricks.sliding_window_view(data, window)[::stride]
    tokens = windows.astype(np.int32)
    if shuffle:
        rng = np.random.default_rng(seed)
        tokens = tokens[rng.permutation(len(tokens))]
    else:
        tokens = tokens.copy()
    if max_seqs is not None:
        tokens = tokens[:max_seqs]
    return tokens
