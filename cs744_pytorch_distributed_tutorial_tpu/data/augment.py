"""On-device augmentation: crop / flip / normalize inside the jitted step.

The reference augments per-sample on the host with torchvision transforms —
``RandomCrop(32, padding=4)``, ``RandomHorizontalFlip``, ``ToTensor``,
``Normalize(mean=[125.3,123.0,113.9]/255, std=[63.0,62.1,66.7]/255)``
(``master/part1/part1.py:66-77``) — paying CPU time and shipping float32
to the device. TPU-first inversion: the host ships raw uint8 batches
(4x less PCIe/ICI traffic) and the whole transform is traced into the
train step, where XLA fuses it into the first conv's input pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# The reference's exact normalization constants (master/part1/part1.py:66-67).
CIFAR10_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR10_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0

_PAD = 4  # RandomCrop(32, padding=4) — master/part1/part1.py:70


def normalize(images: jax.Array) -> jax.Array:
    """uint8 [0,255] -> normalized float32 (ToTensor + Normalize)."""
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(CIFAR10_MEAN)) / jnp.asarray(CIFAR10_STD)


def _crop_flip_one(key: jax.Array, img: jax.Array) -> jax.Array:
    h, w, c = img.shape
    k_h, k_w, k_f = jax.random.split(key, 3)
    padded = jnp.pad(img, ((_PAD, _PAD), (_PAD, _PAD), (0, 0)))
    off_h = jax.random.randint(k_h, (), 0, 2 * _PAD + 1)
    off_w = jax.random.randint(k_w, (), 0, 2 * _PAD + 1)
    cropped = lax.dynamic_slice(padded, (off_h, off_w, 0), (h, w, c))
    return lax.cond(
        jax.random.bernoulli(k_f),
        lambda im: im[:, ::-1, :],
        lambda im: im,
        cropped,
    )


@jax.jit
def random_crop_flip(key: jax.Array, images: jax.Array) -> jax.Array:
    """Per-image RandomCrop(pad 4) + HFlip on an [N, H, W, C] batch.

    One key per image (split from ``key``), vmapped — batched gathers the
    MXU-adjacent VPU handles cheaply; no host-side per-sample Python.
    """
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(_crop_flip_one)(keys, images)


@jax.jit
def augment_train_batch(key: jax.Array, images: jax.Array) -> jax.Array:
    """Full train-time transform: crop + flip on raw uint8, then normalize
    (the reference's transform_train pipeline, master/part1/part1.py:68-73)."""
    return normalize(random_crop_flip(key, images))


@jax.jit
def eval_batch(images: jax.Array) -> jax.Array:
    """Eval-time transform: normalize only (transform_test,
    master/part1/part1.py:75-77)."""
    return normalize(images)
