"""On-device augmentation: crop / flip / normalize inside the jitted step.

The reference augments per-sample on the host with torchvision transforms —
``RandomCrop(32, padding=4)``, ``RandomHorizontalFlip``, ``ToTensor``,
``Normalize(mean=[125.3,123.0,113.9]/255, std=[63.0,62.1,66.7]/255)``
(``master/part1/part1.py:66-77``) — paying CPU time and shipping float32
to the device. TPU-first inversion: the host ships raw uint8 batches
(4x less PCIe/ICI traffic) and the whole transform is traced into the
train step, where XLA fuses it into the first conv's input pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# The reference's exact normalization constants (master/part1/part1.py:66-67).
CIFAR10_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR10_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0

_PAD = 4  # RandomCrop(32, padding=4) — master/part1/part1.py:70


def normalize(images: jax.Array) -> jax.Array:
    """uint8 [0,255] -> normalized float32 (ToTensor + Normalize)."""
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(CIFAR10_MEAN)) / jnp.asarray(CIFAR10_STD)


def _crop_flip_selectors(key: jax.Array, n: int, h: int, w: int):
    """Per-image one-hot row/column selector matrices for crop + flip.

    Returns ``(rows [n,h,h+2P], cols [n,w,w+2P])`` in bfloat16 such that
    contracting them against the padded batch performs, per image, a
    RandomCrop(pad 4) and (with probability 1/2, folded into the column
    permutation) a horizontal flip.
    """
    k_h, k_w, k_f = jax.random.split(key, 3)
    off_h = jax.random.randint(k_h, (n,), 0, 2 * _PAD + 1)
    off_w = jax.random.randint(k_w, (n,), 0, 2 * _PAD + 1)
    flip = jax.random.bernoulli(k_f, shape=(n,))
    rows = jax.nn.one_hot(
        off_h[:, None] + jnp.arange(h)[None, :], h + 2 * _PAD, dtype=jnp.bfloat16
    )
    col_idx = jnp.where(
        flip[:, None], w - 1 - jnp.arange(w)[None, :], jnp.arange(w)[None, :]
    )
    cols = jax.nn.one_hot(
        off_w[:, None] + col_idx, w + 2 * _PAD, dtype=jnp.bfloat16
    )
    return rows, cols


def _crop_flip_matmul(key: jax.Array, images: jax.Array) -> jax.Array:
    """RandomCrop(pad 4) + HFlip as two batched one-hot contractions.

    A vmapped ``dynamic_slice`` crop lowers to per-image gathers, which
    the TPU's VPU executes scalar-ish (measured ~21 ms for a 1024-image
    batch — ~43% of the whole ResNet-18 train step). Re-expressed as two
    batched matmuls against one-hot selector matrices, the same transform
    rides the MXU in ~1 ms. uint8 values (<= 255) are exact in bfloat16
    (8 significant bits), and a one-hot contraction selects a single
    element per output — no accumulation error; output is bfloat16
    holding exact integer pixel values.
    """
    n, h, w, c = images.shape
    rows, cols = _crop_flip_selectors(key, n, h, w)
    padded = jnp.pad(
        images, ((0, 0), (_PAD, _PAD), (_PAD, _PAD), (0, 0))
    ).astype(jnp.bfloat16)
    # y[b,i,l,c] = sum_j rows[b,i,j] * padded[b,j,l,c]
    y = jnp.einsum("bij,bjlc->bilc", rows, padded)
    # out[b,i,k,c] = sum_l cols[b,k,l] * y[b,i,l,c]
    return jnp.einsum("bkl,bilc->bikc", cols, y)


@jax.jit
def random_crop_flip(key: jax.Array, images: jax.Array) -> jax.Array:
    """Per-image RandomCrop(pad 4) + HFlip on an [N, H, W, C] batch.

    MXU path (one-hot contractions, see ``_crop_flip_matmul``); returns
    the input dtype. Exactness of the bfloat16 contraction requires
    pixel values representable in 8 significant bits, so the input must
    be an integer dtype with values <= 255 (CIFAR uint8); float inputs
    would be silently truncated and are rejected.
    """
    if not jnp.issubdtype(images.dtype, jnp.integer):
        raise TypeError(
            f"random_crop_flip expects uint8/integer pixel values, got "
            f"{images.dtype}; the MXU one-hot path is only exact for "
            "<=8-significant-bit values"
        )
    return _crop_flip_matmul(key, images).astype(images.dtype)


@jax.jit
def augment_train_batch(key: jax.Array, images: jax.Array) -> jax.Array:
    """Full train-time transform: crop + flip on raw uint8, then normalize
    (the reference's transform_train pipeline, master/part1/part1.py:68-73).

    The crop/flip output stays bfloat16 (exact for uint8 values) and is
    normalized directly — no round-trip through uint8."""
    return normalize(_crop_flip_matmul(key, images))


@jax.jit
def eval_batch(images: jax.Array) -> jax.Array:
    """Eval-time transform: normalize only (transform_test,
    master/part1/part1.py:75-77)."""
    return normalize(images)
