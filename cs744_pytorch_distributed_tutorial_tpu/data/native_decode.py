"""ctypes bridge to the native CIFAR binary-format decoder.

``decode_cifar_records`` splits raw 3073-byte records (1 label byte +
CHW pixels) into int32 labels and NHWC uint8 images. Dispatches to the
threaded C++ implementation (``native/decode.cpp``) when available, else
to the equivalent NumPy transpose — identical results either way.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.native import load_library

_DEFAULT_THREADS = min(os.cpu_count() or 1, 8)
RECORD_BYTES = 3073


def _configured(lib):
    lib.decode_cifar_u8.restype = ctypes.c_int
    lib.decode_cifar_u8.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
    ]
    return lib


_LIB = None
_LIB_READY = False


def _lib():
    global _LIB, _LIB_READY
    if not _LIB_READY:
        raw = load_library("decode")
        _LIB = _configured(raw) if raw is not None else None
        _LIB_READY = True
    return _LIB


def decode_cifar_records(
    raw: np.ndarray, *, threads: int = _DEFAULT_THREADS
) -> tuple[np.ndarray, np.ndarray]:
    """[N * 3073] (or [N, 3073]) uint8 records -> (images [N,32,32,3] u8,
    labels [N] i32)."""
    raw = np.ascontiguousarray(raw, dtype=np.uint8).reshape(-1)
    if raw.size % RECORD_BYTES:
        raise ValueError(
            f"record buffer of {raw.size} bytes is not a multiple of "
            f"{RECORD_BYTES} (1 label byte + 3x32x32 pixels)"
        )
    n = raw.size // RECORD_BYTES
    lib = _lib()
    if lib is not None:
        images = np.empty((n, 32, 32, 3), np.uint8)
        labels = np.empty((n,), np.int32)
        rc = lib.decode_cifar_u8(
            raw.ctypes.data, n, labels.ctypes.data, images.ctypes.data, threads
        )
        if rc == 0:
            return images, labels
    recs = raw.reshape(n, RECORD_BYTES)
    labels = recs[:, 0].astype(np.int32)
    images = (
        recs[:, 1:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1).copy()
    )
    return images, labels
