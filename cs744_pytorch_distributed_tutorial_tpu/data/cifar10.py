"""CIFAR-10 dataset: torchvision pickle-format reader + synthetic fallback.

The reference loads CIFAR-10 through ``torchvision.datasets.CIFAR10`` with
``download=True`` (``master/part1/part1.py:78-79,86-87``). This module reads
the same on-disk format (the ``cifar-10-batches-py`` pickle tree) without
torchvision, and — because this build environment has no network egress —
falls back to a deterministic *learnable* synthetic set with the same
shapes/dtypes, so every training path stays exercisable end to end.
"""

from __future__ import annotations

import dataclasses
import os
import pickle

import numpy as np

_BATCH_DIR = "cifar-10-batches-py"
_TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_FILE = "test_batch"
# The official BINARY distribution (cifar-10-binary.tar.gz): 3073-byte
# records, decoded by the native C++ core (native/decode.cpp).
_BIN_DIR = "cifar-10-batches-bin"
_BIN_TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
_BIN_TEST_FILE = "test_batch.bin"
NUM_CLASSES = 10


@dataclasses.dataclass(frozen=True)
class CIFAR10Dataset:
    """Raw uint8 NHWC images + int32 labels; augmentation happens on device
    (``data/augment.py``), so the host ships bytes, not floats."""

    train_images: np.ndarray  # [N, 32, 32, 3] uint8
    train_labels: np.ndarray  # [N] int32
    test_images: np.ndarray
    test_labels: np.ndarray
    synthetic: bool = False


def _read_batch(path: str) -> tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = np.asarray(d[b"data"], dtype=np.uint8)
    # stored as [N, 3072] = [N, C=3, H=32, W=32] row-major -> NHWC
    images = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d[b"labels"], dtype=np.int32)
    return images, labels


def _read_binary_batch(path: str) -> tuple[np.ndarray, np.ndarray]:
    from cs744_pytorch_distributed_tutorial_tpu.data.native_decode import (
        decode_cifar_records,
    )

    return decode_cifar_records(np.fromfile(path, dtype=np.uint8))


def synthetic_images(
    train_size: int,
    test_size: int,
    *,
    image_size: int = 32,
    num_classes: int = NUM_CLASSES,
    seed: int = 0,
) -> CIFAR10Dataset:
    """Deterministic synthetic image set with learnable structure, at any
    resolution / class count (the ImageNet-shaped stand-in for scale-out
    benchmarks as well as the CIFAR one).

    Each class gets a smooth random template image; samples are the class
    template plus pixel noise. Same-class images are therefore closer than
    cross-class ones, so a classifier can genuinely learn — the e2e tests
    assert loss decrease and >chance accuracy on it, replacing the
    reference's "eyeball the loss curve on real data" check (SURVEY §4).
    """
    rng = np.random.default_rng(seed)
    # Smooth per-class templates: low-resolution noise upsampled, so
    # templates differ at large spatial scale (survives random crops).
    coarse = rng.uniform(40.0, 215.0, size=(num_classes, 8, 8, 3))
    factor = -(-image_size // 8)  # ceil: upsample then crop to size
    templates = (
        coarse.repeat(factor, axis=1).repeat(factor, axis=2)
    )[:, :image_size, :image_size, :]

    def make_split(n: int) -> tuple[np.ndarray, np.ndarray]:
        labels = rng.integers(0, num_classes, size=n, dtype=np.int32)
        noise = rng.normal(0.0, 24.0, size=(n, image_size, image_size, 3))
        images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
        return images, labels

    train_images, train_labels = make_split(train_size)
    test_images, test_labels = make_split(test_size)
    return CIFAR10Dataset(
        train_images, train_labels, test_images, test_labels, synthetic=True
    )


def synthetic_cifar10(
    train_size: int, test_size: int, seed: int = 0
) -> CIFAR10Dataset:
    """CIFAR-shaped synthetic set (32x32, 10 classes) — byte-identical to
    the round-1 generator (same RNG draw sequence), which the golden-trace
    test and the benchmark depend on."""
    return synthetic_images(train_size, test_size, seed=seed)


def load_cifar10(
    root: str,
    *,
    synthetic: bool | None = None,
    synthetic_train_size: int = 50_000,
    synthetic_test_size: int = 10_000,
    seed: int = 0,
    image_size: int = 32,
    num_classes: int = NUM_CLASSES,
) -> CIFAR10Dataset:
    """Load CIFAR-10 from ``root`` (torchvision on-disk layout), or fall back.

    ``synthetic``: ``None`` = auto (real data if present, else synthetic);
    ``True`` = always synthetic; ``False`` = real data or
    ``FileNotFoundError`` (no silent substitution when the caller demanded
    the real set). Non-CIFAR shapes (``image_size``/``num_classes``
    beyond 32/10 — the ImageNet-shaped configs) are synthetic-only: the
    only real on-disk format this reads is the CIFAR pickle tree.
    """
    cifar_shaped = image_size == 32 and num_classes == NUM_CLASSES
    batch_dir = os.path.join(root, _BATCH_DIR)
    bin_dir = os.path.join(root, _BIN_DIR)
    have_pickle = cifar_shaped and all(
        os.path.exists(os.path.join(batch_dir, f))
        for f in _TRAIN_FILES + [_TEST_FILE]
    )
    have_binary = cifar_shaped and all(
        os.path.exists(os.path.join(bin_dir, f))
        for f in _BIN_TRAIN_FILES + [_BIN_TEST_FILE]
    )
    if synthetic is False and not cifar_shaped:
        raise ValueError(
            f"real data is CIFAR-10 only (32x32, 10 classes); got "
            f"image_size={image_size}, num_classes={num_classes} with "
            "synthetic=False"
        )
    if synthetic is True or (
        synthetic is None and not (have_pickle or have_binary)
    ):
        return synthetic_images(
            synthetic_train_size,
            synthetic_test_size,
            image_size=image_size,
            num_classes=num_classes,
            seed=seed,
        )
    if not (have_pickle or have_binary):
        raise FileNotFoundError(
            f"CIFAR-10 batches not found under {batch_dir!r} (pickle layout) "
            f"or {bin_dir!r} (binary layout) and synthetic=False. Place "
            "either distribution there."
        )
    if have_pickle:
        read, train_files, test_file, d = (
            _read_batch, _TRAIN_FILES, _TEST_FILE, batch_dir
        )
    else:
        read, train_files, test_file, d = (
            _read_binary_batch, _BIN_TRAIN_FILES, _BIN_TEST_FILE, bin_dir
        )
    train_parts = [read(os.path.join(d, f)) for f in train_files]
    train_images = np.concatenate([p[0] for p in train_parts])
    train_labels = np.concatenate([p[1] for p in train_parts])
    test_images, test_labels = read(os.path.join(d, test_file))
    return CIFAR10Dataset(
        train_images, train_labels, test_images, test_labels, synthetic=False
    )
