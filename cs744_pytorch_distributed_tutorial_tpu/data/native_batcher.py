"""ctypes bridge to the native batch-assembly core.

``gather_rows`` is the hot host-side op of the input pipeline: assemble a
batch by gathering example rows into one contiguous buffer (the torch
collate path the reference gets from libtorch via its DataLoader,
``master/part1/part1.py:80-93``). Dispatches to the multithreaded C++
implementation (``native/batcher.cpp``) when the compiler/artifact is
available, else to ``np.take`` — identical results either way.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.native import load_library

_DEFAULT_THREADS = min(os.cpu_count() or 1, 8)


def _configured(lib):
    lib.gather_u8.restype = ctypes.c_int
    lib.gather_u8.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.gather_i32.restype = ctypes.c_int
    lib.gather_i32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int,
    ]
    return lib


_LIB = None
_LIB_READY = False


def _lib():
    global _LIB, _LIB_READY
    if not _LIB_READY:
        raw = load_library("batcher")
        _LIB = _configured(raw) if raw is not None else None
        _LIB_READY = True
    return _LIB


def gather_rows(
    array: np.ndarray, indices: np.ndarray, *, threads: int = _DEFAULT_THREADS
) -> np.ndarray:
    """out[i] = array[indices[i]] for C-contiguous uint8/int32 arrays.

    Equivalent to ``np.take(array, indices, axis=0)``; the native path
    parallelizes the row memcpys. Any precondition the native core can't
    serve (dtype, layout, missing compiler) silently routes to NumPy.
    """
    lib = _lib()
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    usable = (
        lib is not None
        and array.flags.c_contiguous
        and array.dtype in (np.uint8, np.int32)
    )
    if not usable:
        return np.take(array, idx, axis=0)
    n = array.shape[0]
    row_elems = int(np.prod(array.shape[1:], dtype=np.int64))
    out = np.empty((len(idx), *array.shape[1:]), dtype=array.dtype)
    # gather_u8 takes row size in BYTES (== elems for uint8); gather_i32
    # takes it in elements and scales internally.
    fn = lib.gather_u8 if array.dtype == np.uint8 else lib.gather_i32
    rc = fn(
        array.ctypes.data, n, row_elems,
        idx.ctypes.data, len(idx), out.ctypes.data, threads,
    )
    if rc != 0:  # defensive: bad index should be impossible from our samplers
        return np.take(array, idx, axis=0)
    return out
