"""Utilities: logging, step timing, checkpointing."""

from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger, rank_zero_only
from cs744_pytorch_distributed_tutorial_tpu.utils.timing import StepTimer

__all__ = ["get_logger", "rank_zero_only", "StepTimer"]
