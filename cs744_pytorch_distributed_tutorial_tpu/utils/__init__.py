"""Utilities: logging, step timing, checkpointing, profiling, debug."""

from cs744_pytorch_distributed_tutorial_tpu.utils.debug import (
    DivergenceMonitor,
    tree_checksum,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger, rank_zero_only
from cs744_pytorch_distributed_tutorial_tpu.utils.timing import StepTimer

__all__ = [
    "DivergenceMonitor",
    "get_logger",
    "rank_zero_only",
    "StepTimer",
    "tree_checksum",
]
