"""Utilities: logging, step timing, checkpointing, profiling, debug,
failure detection/recovery."""

from cs744_pytorch_distributed_tutorial_tpu.utils.debug import (
    DivergenceMonitor,
    tree_checksum,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    NonFiniteLossError,
    StepWatchdog,
    TrainingFailure,
    run_with_recovery,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger, rank_zero_only
from cs744_pytorch_distributed_tutorial_tpu.utils.timing import StepTimer

__all__ = [
    "DivergenceMonitor",
    "get_logger",
    "NonFiniteLossError",
    "rank_zero_only",
    "run_with_recovery",
    "StepTimer",
    "StepWatchdog",
    "TrainingFailure",
    "tree_checksum",
]
