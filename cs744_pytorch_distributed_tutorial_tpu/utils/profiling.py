"""Tracing/profiling — the subsystem the reference lacks (SURVEY §5.1).

The reference's only instrumentation is wall-clock deltas between
``datetime.now()`` calls printed at batch 10 (``master/part1/part1.py:39-44``),
which on an async-dispatch device measures dispatch, not compute. Here:
real profiler traces (XLA/TPU timeline viewable in TensorBoard /
Perfetto) plus named annotations that show up on the trace, layered over
``jax.profiler``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace for the enclosed region.

    Usage::

        with profiling.trace("/tmp/trace"):
            state, _ = trainer.train_step(state, x, y, key)
            jax.block_until_ready(state.params)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Label a host-side region so it appears on the profiler timeline::

        with profiling.annotate("epoch-0-input"):
            batch = next(loader)
    """
    return jax.profiler.TraceAnnotation(name)


def step_annotation(name: str, step: int):
    """Step marker used by TensorBoard's per-step analysis."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_op_breakdown(
    fn,
    *args,
    iters: int = 3,
    top: int = 20,
    trace_dir: str | None = None,
):
    """Run ``fn(*args)`` ``iters`` times under a profiler trace and return
    per-op DEVICE time — the instrument that found the round-2 bench
    bottlenecks (``benchmarks/ablate.py``).

    Why it exists: on this environment's tunneled TPU backend, host-side
    timers measure per-dispatch overhead (2-10 ms, variable), so
    microbenchmarks of sub-10 ms ops are noise. The device trace is
    ground truth. Works on CPU traces too (tests).

    Returns ``(total_ms, [(ms_per_iter, op_name), ...])`` — device-lane
    durations aggregated by op name, averaged over ``iters``, sorted
    descending. Completion is fenced by fetching a concrete scalar (NOT
    ``block_until_ready`` — unreliable on the tunneled backend).

    Thin shim over ``obs.phases.capture_device_profile`` — graftscope's
    phase profiler and this breakdown share ONE warm-up/fence/trace-parse
    path (the interval-union nesting logic lives there).
    """
    from cs744_pytorch_distributed_tutorial_tpu.obs.phases import (
        capture_device_profile,
    )

    prof = capture_device_profile(
        fn, *args, iters=iters, top=top, trace_dir=trace_dir
    )
    return prof.device_ms, prof.op_rows
