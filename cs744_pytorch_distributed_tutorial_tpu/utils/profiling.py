"""Tracing/profiling — the subsystem the reference lacks (SURVEY §5.1).

The reference's only instrumentation is wall-clock deltas between
``datetime.now()`` calls printed at batch 10 (``master/part1/part1.py:39-44``),
which on an async-dispatch device measures dispatch, not compute. Here:
real profiler traces (XLA/TPU timeline viewable in TensorBoard /
Perfetto) plus named annotations that show up on the trace, layered over
``jax.profiler``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace for the enclosed region.

    Usage::

        with profiling.trace("/tmp/trace"):
            state, _ = trainer.train_step(state, x, y, key)
            jax.block_until_ready(state.params)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Label a host-side region so it appears on the profiler timeline::

        with profiling.annotate("epoch-0-input"):
            batch = next(loader)
    """
    return jax.profiler.TraceAnnotation(name)


def step_annotation(name: str, step: int):
    """Step marker used by TensorBoard's per-step analysis."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)
