"""Tracing/profiling — the subsystem the reference lacks (SURVEY §5.1).

The reference's only instrumentation is wall-clock deltas between
``datetime.now()`` calls printed at batch 10 (``master/part1/part1.py:39-44``),
which on an async-dispatch device measures dispatch, not compute. Here:
real profiler traces (XLA/TPU timeline viewable in TensorBoard /
Perfetto) plus named annotations that show up on the trace, layered over
``jax.profiler``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace for the enclosed region.

    Usage::

        with profiling.trace("/tmp/trace"):
            state, _ = trainer.train_step(state, x, y, key)
            jax.block_until_ready(state.params)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Label a host-side region so it appears on the profiler timeline::

        with profiling.annotate("epoch-0-input"):
            batch = next(loader)
    """
    return jax.profiler.TraceAnnotation(name)


def step_annotation(name: str, step: int):
    """Step marker used by TensorBoard's per-step analysis."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def device_op_breakdown(
    fn,
    *args,
    iters: int = 3,
    top: int = 20,
    trace_dir: str | None = None,
):
    """Run ``fn(*args)`` ``iters`` times under a profiler trace and return
    per-op DEVICE time — the instrument that found the round-2 bench
    bottlenecks (``benchmarks/ablate.py``).

    Why it exists: on this environment's tunneled TPU backend, host-side
    timers measure per-dispatch overhead (2-10 ms, variable), so
    microbenchmarks of sub-10 ms ops are noise. The device trace is
    ground truth. Works on CPU traces too (tests).

    Returns ``(total_ms, [(ms_per_iter, op_name), ...])`` — device-lane
    durations aggregated by op name, averaged over ``iters``, sorted
    descending. Completion is fenced by fetching a concrete scalar (NOT
    ``block_until_ready`` — unreliable on the tunneled backend).
    """
    import collections
    import glob
    import gzip
    import json
    import os
    import shutil
    import tempfile

    def fence(out) -> None:
        leaf = jax.tree.leaves(out)[0]
        float(leaf.ravel().astype("float32")[0])

    fence(fn(*args))  # compile outside the trace
    owns_dir = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="jax_op_breakdown_")
    try:
        with jax.profiler.trace(d):
            out = None
            for _ in range(iters):
                out = fn(*args)
            fence(out)
        paths = sorted(
            glob.glob(os.path.join(d, "plugins/profile/*/*.trace.json.gz"))
        )
        if not paths:
            raise RuntimeError(f"no trace produced under {d}")
        with gzip.open(paths[-1]) as f:
            events = json.load(f)["traceEvents"]
        pids = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e["pid"]] = e["args"].get("name", "")
        durs: collections.Counter = collections.Counter()
        by_lane: dict = collections.defaultdict(list)
        for e in events:
            pname = pids.get(e.get("pid"), "")
            device_lane = (
                "TPU" in pname or "device" in pname.lower() or "/gpu" in pname
            )
            if e.get("ph") == "X" and e.get("dur") and device_lane:
                durs[e["name"]] += e["dur"]
                by_lane[e.get("pid")].append((e.get("ts", 0.0), e["dur"]))
        rows = sorted(
            ((v / iters / 1e3, k) for k, v in durs.items()), reverse=True
        )
        # Per-iter total: sum of TOP-LEVEL device events only. Trace rows
        # nest (a jit_ program contains its op rows; nested jits contain
        # their callees), so summing every event double-counts
        # parent+child, and "largest jit_ entry" under-counts when fn
        # dispatches several programs back-to-back. Nesting is computed
        # per device PID across all its tids: XLA puts the jit_ module
        # event and its op events on DIFFERENT threads of the same
        # device process, so per-(pid, tid) lanes would count both in
        # full. Sort ties by -dur so a parent sharing its first child's
        # start timestamp wins the top-level slot.
        total_us = 0.0
        for lane in by_lane.values():
            lane.sort(key=lambda td: (td[0], -td[1]))
            end = float("-inf")
            for ts, dur in lane:
                if ts >= end:
                    total_us += dur
                    end = ts + dur
                elif ts + dur > end:
                    # Overlapping but not nested (e.g. a DMA straddling
                    # a module boundary): count only the tail beyond the
                    # current busy span — a true interval union.
                    total_us += ts + dur - end
                    end = ts + dur
        return total_us / iters / 1e3, rows[:top]
    finally:
        if owns_dir:
            shutil.rmtree(d, ignore_errors=True)
