"""Structured per-host logging with rank-0 summaries.

The reference logs with bare ``print()`` on every rank independently —
loss every 20 batches, average batch time, eval summary
(``master/part1/part1.py:40,44,60-62``) — and imports ``logging`` without
ever using it (``part1.py:10``). Here: a real logger, prefixed with the
process index on multi-host runs, plus a ``rank_zero_only`` guard so
global summaries print once.
"""

from __future__ import annotations

import logging
import sys
from functools import wraps

import jax

class _RankPrefixFilter(logging.Filter):
    """Stamp each record with the CURRENT ``[proc i/n]`` prefix.

    The prefix must be computed per-record, not cached at handler
    creation: loggers are routinely created at import time, before
    ``jax.distributed`` initializes, and a cached prefix would then be
    silently wrong (absent) for the rest of the run. Worse, it must
    RE-resolve after an elastic re-initialization — a survivor that was
    ``[proc 2/4]`` in generation 0 may be ``[proc 1/3]`` in generation
    1, and a pre-generation prefix would mislabel every post-recovery
    record. Resolution is delegated to
    ``parallel/multihost.py::runtime_labels`` (explicit labels set at
    each re-init > supervisor environment > jax, consulted only when
    its backend is already up — asking earlier would *trigger* backend
    initialization from a log line). Generations after the first carry
    a ``gN`` suffix so interleaved per-generation logs stay separable.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
                runtime_labels,
            )

            labels = runtime_labels()
            n = labels["process_count"]
            gen = labels["generation"]
            suffix = f" g{gen}" if gen > 0 else ""
            record.rank_prefix = (
                f"[proc {labels['process_id']}/{n}{suffix}] " if n > 1 else ""
            )
        except Exception:  # logging must never fail on label resolution
            record.rank_prefix = ""
        return True


def get_logger(name: str = "cs744_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.addFilter(_RankPrefixFilter())
        handler.setFormatter(logging.Formatter("%(rank_prefix)s%(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def rank_zero_only(fn):
    """Run ``fn`` only on process 0 — the reference expresses this as a
    whole separate ``master/`` source tree (SURVEY §1)."""

    @wraps(fn)
    def wrapper(*args, **kwargs):
        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapper
