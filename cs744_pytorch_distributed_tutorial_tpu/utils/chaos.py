"""Fault injection under seeded schedules — chaos-test the recovery ladder.

The reference repo's failure story is "a dead rank hangs Gloo forever"
(SURVEY §5.3); this repo's replacement (watchdog + divergence detection
+ tiered restore + re-mesh, ``utils/failure.py``) is only trustworthy if
it is EXERCISED. This module injects the three production fault shapes
at deterministic points in a run:

- ``"nan"`` — the step executes normally, then its fetched loss is
  poisoned to NaN (flaky-chip / bad-batch analog). ``fit`` raises
  ``NonFiniteLossError`` at the next fetch; recovery restores the newest
  tier and replays.
- ``"device_loss"`` — ``DeviceLossError`` raised before the step runs
  (chip or host dropped out). Recovery escalates to re-meshing onto the
  surviving devices (``parallel/elastic.py``) when a ``remesh`` hook is
  armed.
- ``"sigterm"`` — real ``SIGTERM`` to this process (preemption notice).
  Under ``trap_sigterm`` the signal re-enters the run as a
  ``TrainingFailure`` so the same restart ladder handles it.
- ``"process_kill"`` — real ``SIGKILL`` to a scheduled GLOBAL rank
  (machine death; un-trappable by design). The spec names the target
  (``{"kind": "process_kill", "rank": 2}``) and the monkey is told its
  own rank at construction: only the matching rank dies, every other
  rank proceeds into the step and discovers the death through the
  collective watchdog + supervisor re-election
  (``parallel/multihost.py``). Killing rank 0 exercises coordinator
  re-election. Because the spec is keyed by *cumulative* step index and
  targets a global rank, a re-exec'd survivor that re-parses the same
  schedule can never re-fire it — the dead rank is absent from the new
  generation.

- ``"slow_step"`` (shared with the serve schedule) — a targeted stall
  before the step on one global rank: the seeded straggler whose late
  collective arrival ``obs/fleet.py`` attributes cross-rank.

Faults live in a ``FaultSchedule`` keyed by *cumulative* train-step call
index — the counter spans restarts, so a schedule "fault at call 3"
fires once even though recovery replays calls 0..2. Schedules are
either explicit (``FaultSchedule({3: "nan"})``) or seeded
(``FaultSchedule.seeded(seed, ...)``) for randomized-but-reproducible
chaos runs. Every injection is emitted as a ``kind:"event"`` record
(``chaos_inject``) through the obs sinks, so a chaos run's timeline —
injections, restarts, re-meshes, recovery — is one JSONL stream.

Used by tests/test_chaos.py and the chaos-smoke CI job; the operator
story is in docs/reliability.md.
"""

from __future__ import annotations

import contextlib
import os
import signal
import time
from typing import Any

import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.utils.failure import (
    DeviceLossError,
    EngineCrashError,
    TrainingFailure,
    emit_event,
    run_with_recovery,
)
from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger

# Serve-side kinds target ``ServingEngine._decode_step`` (install via
# ``ServeChaosMonkey``), keyed by cumulative DECODE-step index with the
# same fire-once/spans-restarts semantics as the training kinds:
# - ``"decode_nan"``  — the step runs, then its sampled tokens are
#   poisoned out-of-vocab (NaN-logits analog); the engine's host-side
#   token validation raises ``DecodeNanError``.
# - ``"slow_step"``   — an injected stall before the step (wedged-chip
#   analog); drives the serve watchdog's warn→dump→abort ladder.
# - ``"engine_crash"`` — ``EngineCrashError`` raised BEFORE the step
#   runs (XLA abort analog), so host state stays snapshot-consistent.
SERVE_FAULT_KINDS = ("decode_nan", "slow_step", "engine_crash")
FAULT_KINDS = (
    "nan",
    "device_loss",
    "sigterm",
    "process_kill",
) + SERVE_FAULT_KINDS


class SigtermFailure(TrainingFailure):
    """SIGTERM delivered mid-run (preemption) — recoverable by restart."""


class FaultSchedule:
    """Faults keyed by cumulative train-step call index.

    Each entry fires exactly once (transient faults — the production
    shape recovery can actually beat; a *persistent* fault replays
    after every restart and correctly exhausts ``max_restarts``).
    Values are a fault kind string or a dict like
    ``{"kind": "device_loss", "lost": [4, 5, 6, 7]}``.
    """

    def __init__(self, faults: dict[int, str | dict[str, Any]]):
        self.faults: dict[int, dict[str, Any]] = {}
        for idx, spec in faults.items():
            if isinstance(spec, str):
                spec = {"kind": spec}
            if spec.get("kind") not in FAULT_KINDS:
                raise ValueError(
                    f"fault kind must be one of {FAULT_KINDS}, got "
                    f"{spec.get('kind')!r} at call {idx}"
                )
            if spec["kind"] == "process_kill" and "rank" not in spec:
                raise ValueError(
                    f'process_kill at call {idx} needs a target: '
                    f'{{"kind": "process_kill", "rank": <global rank>}}'
                )
            self.faults[int(idx)] = dict(spec)

    @classmethod
    def seeded(
        cls,
        seed: int,
        n_calls: int,
        rate: float = 0.1,
        kinds: tuple[str, ...] = ("nan",),
        first_call: int = 1,
        lost: tuple[int, ...] = (),
        kill_rank: int | None = None,
    ) -> "FaultSchedule":
        """Randomized-but-reproducible schedule: each call index in
        ``[first_call, n_calls)`` faults with probability ``rate``, kind
        drawn uniformly from ``kinds``. Same seed -> same chaos, so a
        failing chaos run replays exactly."""
        rng = np.random.default_rng(seed)
        faults: dict[int, dict[str, Any]] = {}
        for idx in range(first_call, n_calls):
            if rng.random() < rate:
                kind = kinds[int(rng.integers(len(kinds)))]
                spec: dict[str, Any] = {"kind": kind}
                if kind == "device_loss" and lost:
                    spec["lost"] = tuple(lost)
                if kind == "process_kill":
                    spec["rank"] = 0 if kill_rank is None else int(kill_rank)
                faults[idx] = spec
        return cls(faults)

    def pop(self, idx: int) -> dict[str, Any] | None:
        return self.faults.pop(idx, None)

    def __len__(self) -> int:
        return len(self.faults)


class ChaosMonkey:
    """Wrap a trainer's ``train_step`` to fire a ``FaultSchedule``.

    The call counter is owned by the monkey, not the wrapper, so it is
    cumulative across restarts AND across re-meshes (``install`` the
    same monkey on the replacement trainer — ``run_chaos`` does this
    automatically). ``injected`` records ``(call_index, kind)`` for
    assertions.

    ``rank`` is this process's GLOBAL rank for ``process_kill`` /
    ``slow_step`` targeting (faults aimed at another rank are skipped
    silently); ``first_call`` offsets the cumulative index for a
    process that resumed mid-run — a re-exec'd survivor starting at
    step K passes ``first_call=K`` so the schedule keys keep meaning
    absolute step indices across generations.

    ``slow_step`` in a TRAINING schedule is the seeded-straggler fault:
    ``{"kind": "slow_step", "rank": 3, "stall_s": 0.25}`` stalls only
    the targeted rank before its step, so every peer arrives at the
    collective early and waits — the asymmetry ``obs/fleet.py``'s
    cross-rank skew attribution exists to name. ``sleep`` is injectable
    so stalls are testable without wall time."""

    def __init__(
        self,
        schedule: FaultSchedule,
        telemetry: Any = None,
        *,
        rank: int | None = None,
        first_call: int = 0,
        sleep: Any = time.sleep,
    ):
        self.schedule = schedule
        self.telemetry = telemetry
        self.rank = rank
        self.first_call = int(first_call)
        self.sleep = sleep
        self.calls = 0  # cumulative train_step invocations, all restarts
        self.injected: list[tuple[int, str]] = []
        self._log = get_logger()

    def _inject(self, idx: int, kind: str) -> None:
        self.injected.append((idx, kind))
        self._log.warning("chaos: injecting %r at call %d", kind, idx)
        emit_event(self.telemetry, "chaos_inject", fault=kind, call=idx)

    def install(self, trainer: Any) -> Any:
        """Monkeypatch ``trainer.train_step`` (works for both engines:
        the metrics dict is the tuple's last element). Returns the
        trainer for chaining inside a ``remesh`` hook."""
        orig = trainer.train_step

        def chaotic_step(*args, **kwargs):
            idx = self.first_call + self.calls
            self.calls += 1
            fault = self.schedule.pop(idx)
            kind = fault["kind"] if fault else None
            if kind == "process_kill":
                if self.rank is not None and int(fault["rank"]) == self.rank:
                    self._inject(idx, kind)
                    # SIGKILL cannot be trapped or flushed-after: the
                    # injection event above must already be durable
                    # (JsonlSink flushes per record; the rendezvous
                    # store appends line-atomically).
                    os.kill(os.getpid(), signal.SIGKILL)
                # Another rank's death (or a re-parsed schedule whose
                # target is already dead): not our fault to fire. The
                # step proceeds and the collective watchdog reports
                # what the peer's SIGKILL did to it.
                kind = None
            if kind == "slow_step":
                target = fault.get("rank")
                if (
                    target is None
                    or self.rank is None
                    or int(target) == self.rank
                ):
                    self._inject(idx, kind)
                    self.sleep(float(fault.get("stall_s", 0.5)))
                # The step itself proceeds normally — the fault is the
                # stall, and only on the targeted rank.
                kind = None
            if kind == "device_loss":
                self._inject(idx, kind)
                raise DeviceLossError(step=idx, lost=fault.get("lost", ()))
            if kind == "sigterm":
                self._inject(idx, kind)
                # Real signal to this process: delivery is checked at
                # the next bytecode, so under trap_sigterm this raises
                # SigtermFailure before the step executes — exactly a
                # preemption notice landing between steps.
                os.kill(os.getpid(), signal.SIGTERM)
            result = orig(*args, **kwargs)
            if kind == "nan":
                self._inject(idx, kind)
                import jax.numpy as jnp

                metrics = dict(result[-1], loss=jnp.float32(float("nan")))
                result = (*result[:-1], metrics)
            return result

        trainer.train_step = chaotic_step
        return trainer


class ServeChaosMonkey(ChaosMonkey):
    """Fire a ``FaultSchedule`` of serve kinds on a ``ServingEngine``.

    Wraps ``engine._decode_step`` instead of ``trainer.train_step``; the
    cumulative call counter is the DECODE-step index and — exactly like
    the training monkey — is owned by the monkey, so re-``install`` on
    the replacement engine after a restart and a popped fault can never
    re-fire while the replayed steps count past it.

    ``sleep`` is injectable so ``slow_step`` stalls are testable without
    real wall time."""

    def __init__(
        self,
        schedule: FaultSchedule,
        telemetry: Any = None,
        *,
        first_call: int = 0,
        sleep: Any = time.sleep,
    ):
        super().__init__(schedule, telemetry, first_call=first_call)
        self.sleep = sleep

    def install(self, engine: Any) -> Any:
        """Monkeypatch ``engine._decode_step``. Returns the engine for
        chaining inside ``run_serve_with_recovery``'s rebuild."""
        orig = engine._decode_step

        def chaotic_decode(*args, **kwargs):
            idx = self.first_call + self.calls
            self.calls += 1
            fault = self.schedule.pop(idx)
            kind = fault["kind"] if fault else None
            if kind == "engine_crash":
                self._inject(idx, kind)
                # Before the step: no donated buffers consumed, no host
                # bookkeeping advanced — snapshot() after the raise
                # describes exactly the pre-step world.
                raise EngineCrashError(step=idx)
            if kind == "slow_step":
                self._inject(idx, kind)
                self.sleep(float(fault.get("stall_s", 0.5)))
            pages, tok = orig(*args, **kwargs)
            if kind == "decode_nan":
                self._inject(idx, kind)
                import jax.numpy as jnp

                # NaN logits make every sample garbage; -1 is the
                # canonical out-of-vocab poison the engine's host-side
                # validation (DecodeNanError) is specified to catch.
                tok = jnp.full_like(tok, -1)
            return pages, tok

        # Post-run tooling (obs/serve_trace.py::profile_serve_programs)
        # unwraps to reach the jitted original's .lower()/AOT surface —
        # and to keep profiling re-runs off the fault counter.
        chaotic_decode.__wrapped__ = orig
        engine._decode_step = chaotic_decode
        return engine


@contextlib.contextmanager
def trap_sigterm():
    """Convert SIGTERM into a catchable ``SigtermFailure`` for the scope.

    Python delivers the signal on the main thread between bytecodes, so
    the exception surfaces inside the training loop and flows into
    ``run_with_recovery``'s ladder like any other ``TrainingFailure``.
    The previous handler (e.g. ``obs/flight.py``'s dumping handler) is
    restored on exit."""

    def _raise(signum, frame):
        raise SigtermFailure("SIGTERM received (preemption)")

    prev = signal.signal(signal.SIGTERM, _raise)
    try:
        yield
    finally:
        signal.signal(signal.SIGTERM, prev)


def run_chaos(
    trainer: Any,
    schedule: FaultSchedule | ChaosMonkey,
    *,
    telemetry: Any = None,
    remesh: Any = None,
    **recovery_kwargs: Any,
):
    """Install the chaos monkey, trap SIGTERM, and run the recovery
    ladder. ``remesh`` (``parallel/elastic.py::default_remesh``) is
    wrapped so the replacement trainer is re-instrumented — the fault
    schedule keeps firing across the re-mesh. Returns
    ``(*fit_result, restarts, monkey)``."""
    monkey = (
        schedule
        if isinstance(schedule, ChaosMonkey)
        else ChaosMonkey(schedule, telemetry=telemetry)
    )
    monkey.install(trainer)

    chaotic_remesh = None
    if remesh is not None:

        def chaotic_remesh(tr, failure):
            return monkey.install(remesh(tr, failure))

    with trap_sigterm():
        result = run_with_recovery(
            trainer,
            telemetry=telemetry,
            remesh=chaotic_remesh,
            **recovery_kwargs,
        )
    return (*result, monkey)
