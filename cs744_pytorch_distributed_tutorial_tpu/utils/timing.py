"""Per-step timing that respects async dispatch and compilation.

The reference instruments wall-clock per batch with ``datetime.now()``
captured at batches divisible by 20 and the delta printed at batch 10
divided by 9 (``master/part1/part1.py:39-44``) — which silently folds any
warm-up cost into the average and only works because batch 0 triggers the
``% 20`` branch (SURVEY §5.1). On TPU, dispatch is asynchronous and step
0 pays XLA compilation, so a meaningful timer must (a) block on the
step's outputs before reading the clock and (b) exclude the compile step.
"""

from __future__ import annotations

import time


class StepTimer:
    """Records per-step wall-clock; averages a window excluding step 0.

    Call ``tick()`` after fetching a concrete value from the step (e.g.
    ``float(output)``) — a host round-trip is the reliable completion
    fence; ``jax.block_until_ready`` can return early on this
    environment's tunneled TPU backend (see ``bench.py``). ``window`` is
    the inclusive
    (first, last) step range averaged — default (1, 10), the reference's
    batches-1-to-10 window with compile excluded.
    """

    def __init__(self, window: tuple[int, int] = (1, 10)):
        self.window = window
        self.durations: list[float] = []
        self._last: float | None = None

    def start(self) -> None:
        self._last = time.perf_counter()

    def tick(self) -> float:
        now = time.perf_counter()
        if self._last is None:
            self._last = now
            return 0.0
        dt = now - self._last
        self._last = now
        self.durations.append(dt)
        return dt

    @property
    def steps_recorded(self) -> int:
        return len(self.durations)

    def window_average(self) -> float | None:
        """Mean seconds/step over the configured window (1-indexed steps),
        or None until the window is complete."""
        first, last = self.window
        if len(self.durations) < last + 1:
            return None
        return sum(self.durations[first : last + 1]) / (last - first + 1)

    def average(self, skip: int = 1) -> float | None:
        """Mean over all recorded steps, skipping the first ``skip``."""
        if len(self.durations) <= skip:
            return None
        span = self.durations[skip:]
        return sum(span) / len(span)
