"""Checkpoint/resume via Orbax — a capability *addition* over the
reference, which has none: no ``torch.save``/``load`` anywhere, training
is one epoch from scratch (``master/part1/part1.py:101``; SURVEY §5.4).

Saves the full ``TrainState`` pytree (params, per-replica BN stats,
optimizer state, step) with its shardings. Restore is **mesh-elastic**:
a checkpoint written on an N-device mesh loads into an M-device
trainer — world-size-shaped leaves (the per-replica ``[num_devices,
...]`` BN-stats axis) are sliced (shrinking) or cyclically tiled
(growing) to the new world, everything else redistributes via the
template's shardings. The reference's fixed ``[0,1,2,3]`` world
(``master/part2a/part2a.py:32``) rules this out by construction.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def _path_key(path) -> str:
    parts = []
    for p in path:
        for attr in ("name", "key", "idx"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def adapt_host_leaf(path, saved, like, adapt=None):
    """Resize one saved leaf to the template leaf ``like``'s shape.

    Same-shape leaves pass through untouched. For shape mismatches the
    ``adapt`` hook is consulted first (``adapt(path_key, saved_host,
    like) -> array | None`` — the ZeRO engines re-chunk flat shard state
    here), then the default world-size rule: the leading axis slices
    down or tiles cyclically up; any other mismatch is an error. Shared
    by ``Checkpointer.restore_latest`` (disk) and
    ``utils/memstore.py::ReplicatedSnapshot`` (host RAM) so both restore
    tiers are mesh-elastic with identical semantics."""
    if isinstance(saved, jax.Array) and not saved.is_fully_addressable:
        if saved.shape == like.shape:
            # Same-shape leaf already living on a process-spanning
            # sharding: device_get would raise; the caller's
            # place_state/host_to_global handles any re-placement.
            return saved
        raise ValueError(
            "mesh-elastic adaptation of a process-spanning leaf "
            f"(shape {saved.shape} -> {like.shape}) is not "
            "supported: restore on a single-host mesh first, or "
            "match the saved world size"
        )
    saved = np.asarray(jax.device_get(saved))
    if saved.shape == like.shape:
        return saved
    if adapt is not None:
        out = adapt(_path_key(path), saved, like)
        if out is not None:
            return out
    if saved.shape[1:] != like.shape[1:] or saved.ndim == 0:
        raise ValueError(
            f"cannot adapt checkpoint leaf of shape {saved.shape} to "
            f"{like.shape}: only the leading (world-size) axis may "
            "differ"
        )
    n = like.shape[0]
    if saved.shape[0] >= n:
        return saved[:n]
    reps = -(-n // saved.shape[0])
    return np.tile(saved, (reps,) + (1,) * (saved.ndim - 1))[:n]


def place_host_leaf(leaf, like):
    """Commit one (usually host-numpy) leaf to the template leaf's
    sharding. Leaving restored leaves uncommitted lets jit's donation
    pairing match a donated input against a same-shaped output of a
    DIFFERENT sharding (observed on the mixed chunked/natural ZeRO x EP
    layout: an XLA "aliased input/output size" crash on the first
    resumed step) — so every restore tier places through here."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        return leaf  # process-spanning: caller re-places
    if isinstance(like, jax.Array):
        if (
            isinstance(leaf, jax.Array)
            and leaf.committed
            and leaf.sharding.is_equivalent_to(like.sharding, leaf.ndim)
        ):
            # Already a committed device array on the template's
            # sharding: the np.asarray round-trip would pull every
            # shard to host and re-upload for nothing, and the
            # donation-pairing guarantee above already holds.
            return leaf
        arr = np.asarray(leaf)
        if not like.sharding.is_fully_addressable:
            # Multi-process template (elastic restore after a re-exec):
            # device_put rejects process-spanning shardings. Build the
            # global array from this process's addressable shards — the
            # host copy is the full global value on every process, so
            # indexing by shard is exact.
            return jax.make_array_from_callback(
                arr.shape, like.sharding, lambda idx: arr[idx]
            )
        return jax.device_put(arr, like.sharding)
    return leaf


def adapt_and_place(saved_tree, template, adapt=None):
    """Full restore discipline over a saved pytree: per-leaf elastic
    resize (``adapt_host_leaf``) then commit to the template's shardings
    (``place_host_leaf``). ``saved_tree`` must match ``template``'s
    structure (host numpy or device arrays per leaf)."""
    adapted = jax.tree_util.tree_map_with_path(
        lambda p, s, like: adapt_host_leaf(p, s, like, adapt),
        saved_tree,
        template,
    )
    return jax.tree.map(place_host_leaf, adapted, template)


class Checkpointer:
    """Thin Orbax CheckpointManager wrapper keyed by training step.

    Class-wide ``total_restores``/``total_saves`` count actual
    filesystem restore/save operations across every instance — the
    chaos tests (tests/test_chaos.py) read them to PROVE the in-memory
    snapshot recovery path (``utils/memstore.py``) touched no disk.
    """

    total_restores = 0  # filesystem restores, across all instances
    total_saves = 0  # filesystem saves, across all instances

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, state: Any, *, force: bool = False, wait: bool = False) -> None:
        """Persist ``state`` keyed by its step. ASYNC by default: the
        device->host copy happens before returning (so the training loop
        may immediately donate/overwrite the live buffers), while
        serialization and disk I/O proceed on Orbax's background thread —
        the train loop never stalls on the filesystem. Orbax commits a
        step atomically, so a crash mid-write never leaves a readable
        half-checkpoint; ``restore_latest``/``close`` synchronize first.
        ``wait=True`` blocks until durable (tests, final saves)."""
        step = int(jax.device_get(state.step))
        if force and self.manager.latest_step() == step:
            return  # already saved at this step
        Checkpointer.total_saves += 1
        self.manager.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        """Newest durable step, or None with no checkpoints. Fences
        in-flight async saves first so the answer reflects what
        ``restore_latest`` would actually load (the restore-tier
        arbitration in the engines' ``fit`` compares this against the
        in-memory snapshot's step)."""
        self.manager.wait_until_finished()
        return self.manager.latest_step()

    def restore_latest(self, template: Any, adapt=None) -> Any | None:
        """Restore the newest checkpoint into ``template``'s structure and
        shardings; None if the directory has no checkpoints. Leaves whose
        SAVED leading axis differs from the template's (a different world
        size) are resized — slice down, or tile cyclically up.

        ``adapt`` customizes that resizing per leaf (the ZeRO engines'
        flat-chunk state re-chunks rather than slices): called as
        ``adapt(path_key, saved_host_array, template_leaf)`` for every
        shape-mismatched fully-addressable leaf, it returns the adapted
        host array or None to fall through to the default slice/tile."""
        self.manager.wait_until_finished()  # in-flight saves land first
        step = self.manager.latest_step()
        if step is None:
            return None
        Checkpointer.total_restores += 1
        try:
            return self.manager.restore(
                step, args=self._ocp.args.StandardRestore(template)
            )
        except ValueError:
            pass  # shape mismatch: mesh-elastic path below

        # Build a restore target with the SAVED shapes (matched by path
        # name — metadata is dict-structured, the template may be a
        # dataclass), restore at those shapes, then adapt leading axes.
        meta = self.manager.item_metadata(step)
        meta_by_path = {
            _path_key(p): m
            for p, m in jax.tree_util.tree_flatten_with_path(meta)[0]
        }

        def _elastic_sharding(shape):
            """Host-local sharding for a saved-shape restore target: the
            recorded sharding can name device ids absent on the
            (different-world) restoring host, and a single-device target
            would concentrate large leaves on one HBM. Spread the
            leading (world-sized) axis over as many local devices as
            divide it."""
            devs = jax.local_devices()
            if not shape:
                return jax.sharding.SingleDeviceSharding(devs[0])
            n = 1
            for d in range(min(len(devs), shape[0]), 0, -1):
                if shape[0] % d == 0:
                    n = d
                    break
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(np.asarray(devs[:n]), ("elastic",))
            return NamedSharding(
                mesh, PartitionSpec("elastic", *([None] * (len(shape) - 1)))
            )

        def saved_shaped(path, leaf):
            m = meta_by_path.get(_path_key(path))
            if m is None or tuple(m.shape) == tuple(leaf.shape):
                return leaf
            return jax.ShapeDtypeStruct(
                tuple(m.shape),
                leaf.dtype,
                sharding=_elastic_sharding(tuple(m.shape)),
            )

        target = jax.tree_util.tree_map_with_path(saved_shaped, template)
        raw = self.manager.restore(
            step, args=self._ocp.args.StandardRestore(target)
        )
        # Elastic resize + commit to the template's shardings — the
        # module-level discipline shared with ReplicatedSnapshot.
        return adapt_and_place(raw, template, adapt)

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()
