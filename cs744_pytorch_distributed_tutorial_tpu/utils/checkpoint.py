"""Checkpoint/resume via Orbax — a capability *addition* over the
reference, which has none: no ``torch.save``/``load`` anywhere, training
is one epoch from scratch (``master/part1/part1.py:101``; SURVEY §5.4).

Saves the full ``TrainState`` pytree (params, per-replica BN stats,
optimizer state, step) with its shardings; restore round-trips through
the same mesh layout.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class Checkpointer:
    """Thin Orbax CheckpointManager wrapper keyed by training step."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, state: Any, *, force: bool = False) -> None:
        step = int(jax.device_get(state.step))
        if force and self.manager.latest_step() == step:
            return  # already saved at this step
        self.manager.save(step, args=self._ocp.args.StandardSave(state))
        self.manager.wait_until_finished()

    def restore_latest(self, template: Any) -> Any | None:
        """Restore the newest checkpoint into ``template``'s structure and
        shardings; None if the directory has no checkpoints."""
        step = self.manager.latest_step()
        if step is None:
            return None
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(template)
        )

    def close(self) -> None:
        self.manager.close()
