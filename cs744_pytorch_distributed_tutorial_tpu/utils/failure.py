"""Failure detection and recovery — the subsystem the reference lacks.

The reference has NO failure story: if any rank dies, its Gloo
collectives hang or error with no retry and no elasticity (SURVEY §5.3);
one latent bug — the slave's unmatched ``isend`` of eval results
(``slave/part2b/part2b.py:67-69``) — would itself hang a stricter
backend. The TPU-native stance: XLA collectives inside one jitted
program can't race, but a step CAN hang (wedged chip, dead host in the
coordination service) or diverge (non-finite loss). This module supplies
the three pieces the reference is missing:

1. ``StepWatchdog`` — host-side hang detection. The train loop arms it
   around each step; if the step doesn't complete within the timeout the
   watchdog fires on its own thread: logs, dumps all Python stacks
   (``faulthandler``) so the operator sees WHERE the host is blocked
   (usually a device transfer behind a dead collective), and invokes an
   optional callback (in multi-host deployments: abort the process so
   the coordination service can restart the job).
2. ``NonFiniteLossError`` — divergence detection. ``Trainer.fit`` raises
   it when a fetched loss is NaN/inf (checked at logging granularity, so
   detection costs zero extra host<->device transfers).
3. ``run_with_recovery`` — checkpoint/restart elasticity. Wraps a
   trainer's ``fit``; on a detected failure it re-enters ``fit``, which
   restores the newest checkpoint (``utils/checkpoint.py``) and resumes
   from the step it recorded — up to ``max_restarts`` times.
"""

from __future__ import annotations

import contextlib
import faulthandler
import json
import sys
import threading
import time
from typing import Any, Callable

from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger


class TrainingFailure(RuntimeError):
    """Base class for detected training failures (recoverable by restart)."""


class NonFiniteLossError(TrainingFailure):
    """Loss came back NaN/inf — the run has diverged."""

    def __init__(self, step: int, loss: float):
        super().__init__(f"non-finite loss {loss!r} at step {step}")
        self.step = step
        self.loss = loss


class StepWatchdog:
    """Detect hung training steps from the host side.

    Usage::

        wd = StepWatchdog(timeout_s=300)
        for batch in loader:
            with wd.watch():
                state, metrics = train_step(state, *batch)
        wd.close()

    If a watched section outlives ``timeout_s`` the watchdog — on its own
    long-lived monitor thread, since the training thread is the one
    that's stuck — logs a critical message, dumps every thread's Python
    stack to stderr, and calls ``on_hang(elapsed_s)``. It fires at most
    once per watched section and never interrupts the training thread
    itself: detection, not preemption (in multi-host runs the callback
    should abort the process and let the coordination service restart
    the job).

    One monitor thread serves the whole run (arm/disarm just move a
    deadline under a condition variable — no per-step thread churn), and
    once ``disarm`` returns, no fire for that section can happen: the
    deadline check AND the report itself run under the lock, so a
    concurrent ``disarm`` either cancels the fire or blocks until the
    report finishes.
    """

    def __init__(
        self,
        timeout_s: float,
        on_hang: Callable[[float], None] | None = None,
        dump_stacks: bool = True,
        metric_ring: Any | None = None,
        ring_tail: int = 32,
        flight_recorder: Any | None = None,
    ):
        self.timeout_s = timeout_s
        self.on_hang = on_hang
        self.dump_stacks = dump_stacks
        # Any object with .tail(n) -> list[dict] (obs.sinks.RingSink):
        # on firing, the last N step records are flushed to the log so
        # the operator sees what the run was doing when it wedged —
        # stacks say WHERE the host is stuck, the ring says WHAT the
        # training was converging (or not) toward.
        self.metric_ring = metric_ring
        self.ring_tail = ring_tail
        # obs.flight.FlightRecorder (anything with .dump(reason, **kw)):
        # adds the phase-timing tail and straggler stats to the report —
        # the ring says what the LOSS was doing, the flight recorder
        # says what the STEP TIMES were doing before the hang.
        self.flight_recorder = flight_recorder
        self.fired = 0  # total hang detections (for tests/metrics)
        self._log = get_logger()
        self._cv = threading.Condition()
        self._deadline: float | None = None  # None = disarmed
        self._armed_timeout = timeout_s
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, timeout_s: float | None = None) -> None:
        """Start the countdown for one section; ``timeout_s`` overrides the
        default for sections with a different latency envelope (e.g. a
        checkpoint save)."""
        with self._cv:
            self._armed_timeout = timeout_s if timeout_s is not None else self.timeout_s
            self._deadline = time.monotonic() + self._armed_timeout
            self._cv.notify()

    def disarm(self) -> None:
        """The step completed in time; stop the countdown."""
        with self._cv:
            self._deadline = None
            self._cv.notify()

    @contextlib.contextmanager
    def watch(self):
        """Context manager: ``arm`` on enter, ``disarm`` on exit (also on
        exception paths)."""
        self.arm()
        try:
            yield self
        finally:
            self.disarm()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._deadline = None
            self._cv.notify()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if self._deadline is None:
                    self._cv.wait()
                    continue
                now = time.monotonic()
                remaining = self._deadline - now
                if remaining > 0:
                    self._cv.wait(timeout=remaining)
                    continue
                # Expired while still armed: consume the deadline (fire
                # once per section) and report WHILE HOLDING the lock, so
                # disarm() can never return with a fire still pending.
                elapsed = self._armed_timeout + (now - self._deadline)
                self._deadline = None
                self._fire(elapsed, self._armed_timeout)

    def _fire(self, elapsed_s: float, timeout_s: float) -> None:
        self.fired += 1
        self._log.critical(
            "watchdog: training step exceeded %.1fs (%.1fs elapsed) — host is "
            "likely blocked on a device transfer behind a hung collective; "
            "dumping stacks",
            timeout_s,
            elapsed_s,
        )
        if self.dump_stacks:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        if self.metric_ring is not None:
            try:
                records = self.metric_ring.tail(self.ring_tail)
            except Exception as e:  # never let telemetry break the report
                self._log.critical("watchdog: metric ring unreadable: %r", e)
                records = []
            if records:
                self._log.critical(
                    "watchdog: last %d metric records before hang:", len(records)
                )
                for rec in records:
                    self._log.critical("watchdog:   %s", json.dumps(rec, default=str))
        if self.flight_recorder is not None:
            try:
                self.flight_recorder.dump(
                    "watchdog", elapsed_s=elapsed_s, timeout_s=timeout_s
                )
            except Exception as e:  # never let telemetry break the report
                self._log.critical("watchdog: flight recorder dump failed: %r", e)
        if self.on_hang is not None:
            self.on_hang(elapsed_s)


def run_with_recovery(
    trainer: Any,
    *,
    max_restarts: int = 2,
    fit_args: tuple = (),
    fit_kwargs: dict[str, Any] | None = None,
):
    """Run ``trainer.fit`` with checkpoint/restart recovery.

    On a ``TrainingFailure`` (e.g. ``NonFiniteLossError``) the run is
    restarted: ``fit`` restores the newest checkpoint for its
    ``checkpoint_dir`` and resumes at the recorded step, so work since
    the last checkpoint — including the steps that produced the
    divergence — is replayed from known-good state. Requires
    ``trainer.cfg.checkpoint_dir`` (without it there is nothing to
    restart FROM, and the failure re-raises immediately).

    Works with either engine — the CIFAR ``Trainer`` (``fit()`` ->
    ``(state, history)``) or ``LMTrainer`` (``fit(tokens, steps)`` ->
    ``(params, opt_state, losses)``): returns ``fit``'s tuple with
    ``restarts`` appended.
    """
    log = get_logger()
    if not getattr(trainer.cfg, "checkpoint_dir", None):
        raise ValueError(
            "run_with_recovery needs cfg.checkpoint_dir: restart-based "
            "recovery resumes from the newest checkpoint"
        )
    kwargs = fit_kwargs or {}
    restarts = 0
    while True:
        try:
            result = trainer.fit(*fit_args, **kwargs)
            return (*result, restarts)
        except TrainingFailure as e:
            restarts += 1
            if restarts > max_restarts:
                log.critical(
                    "giving up after %d restarts (last failure: %s)", restarts - 1, e
                )
                raise
            log.error(
                "training failure (%s); restart %d/%d from newest checkpoint",
                e,
                restarts,
                max_restarts,
            )
