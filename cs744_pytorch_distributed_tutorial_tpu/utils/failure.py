"""Failure detection and recovery — the subsystem the reference lacks.

The reference has NO failure story: if any rank dies, its Gloo
collectives hang or error with no retry and no elasticity (SURVEY §5.3);
one latent bug — the slave's unmatched ``isend`` of eval results
(``slave/part2b/part2b.py:67-69``) — would itself hang a stricter
backend. The TPU-native stance: XLA collectives inside one jitted
program can't race, but a step CAN hang (wedged chip, dead host in the
coordination service) or diverge (non-finite loss). This module supplies
the three pieces the reference is missing:

1. ``StepWatchdog`` — host-side hang detection. The train loop arms it
   around each step; if the step doesn't complete within the timeout the
   watchdog fires on its own thread: logs, dumps all Python stacks
   (``faulthandler``) so the operator sees WHERE the host is blocked
   (usually a device transfer behind a dead collective), and invokes an
   optional callback (in multi-host deployments: abort the process so
   the coordination service can restart the job).
2. ``NonFiniteLossError`` — divergence detection. ``Trainer.fit`` raises
   it when a fetched loss is NaN/inf (checked at logging granularity, so
   detection costs zero extra host<->device transfers).
3. ``run_with_recovery`` — restart elasticity with a graduated
   escalation ladder. Wraps a trainer's ``fit``; on a detected failure
   it re-enters ``fit``, which restores the newest state tier — the
   in-memory replicated snapshot (``utils/memstore.py``, zero
   filesystem reads) when one is newer, else the newest disk checkpoint
   (``utils/checkpoint.py``) — and resumes from the recorded step, up
   to ``max_restarts`` times with exponential backoff between attempts.
   A ``DeviceLossError`` escalates to re-meshing onto the surviving
   devices (``parallel/elastic.py``) before the restart. Every
   transition lands on the obs sinks as a ``kind:"event"`` record.

The fault-injection harness that exercises all of this under seeded
schedules lives in ``utils/chaos.py``; docs/reliability.md walks the
full ladder.
"""

from __future__ import annotations

import contextlib
import faulthandler
import json
import sys
import threading
import time
import traceback as _traceback
from typing import Any, Callable

import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.utils.logging import get_logger


class TrainingFailure(RuntimeError):
    """Base class for detected training failures (recoverable by restart)."""


class NonFiniteLossError(TrainingFailure):
    """Loss came back NaN/inf — the run has diverged."""

    def __init__(self, step: int, loss: float):
        super().__init__(f"non-finite loss {loss!r} at step {step}")
        self.step = step
        self.loss = loss


class DeviceLossError(TrainingFailure):
    """A device (or its host) dropped out of the mesh mid-run.

    Retrying on the same mesh cannot succeed — the surviving world must
    re-mesh (``parallel/elastic.py``). ``lost`` carries the dead device
    ids (what the runtime's health check, or the chaos harness's seeded
    schedule, reported); ``run_with_recovery`` hands them to its
    ``remesh`` callback."""

    def __init__(self, step: int, lost=()):
        lost = tuple(lost)
        super().__init__(
            f"device loss at step {step}"
            + (f" (lost devices {list(lost)})" if lost else "")
        )
        self.step = step
        self.lost = lost


class ProcessLossError(TrainingFailure):
    """A peer PROCESS died mid-run (SIGKILLed rank, dead host).

    The process-level analog of ``DeviceLossError``: retrying inside
    this generation cannot succeed — every cross-process collective
    still references the dead rank's address. The survivors must leave
    the generation (``parallel/multihost.py``'s supervisor re-execs them
    into generation g+1 on the shrunk world) and resume from the newest
    durable tier. Raised by ``CollectiveWatchdog.check()`` between
    steps; a survivor blocked INSIDE a collective cannot catch anything,
    so the in-collective path exits with ``EXIT_PROCESS_LOSS`` instead.
    ``dead`` carries the dead GLOBAL ranks the membership store
    reported."""

    def __init__(self, generation: int = 0, dead=()):
        dead = tuple(int(r) for r in dead)
        super().__init__(
            f"process loss in generation {generation}"
            + (f" (dead ranks {list(dead)})" if dead else "")
        )
        self.generation = generation
        self.dead = dead


class ServeFailure(TrainingFailure):
    """Base class for detected SERVING-engine failures.

    The serving analog of ``TrainingFailure``: recoverable by rebuilding
    the engine and resuming from ``ServingEngine.snapshot()`` (host-side
    request state only — the snapshot taken AFTER the failing step is
    consistent because the engine raises before any per-step request
    bookkeeping). ``serve/guard.py::run_serve_with_recovery`` is the
    ladder that catches these. Defined here (not in ``serve/``) so
    ``utils/chaos.py`` can raise them without an import cycle."""


class DecodeNanError(ServeFailure):
    """A decode step produced out-of-vocabulary tokens — the logits were
    NaN/inf-poisoned (real numerical blowup, or the chaos harness's
    ``decode_nan`` fault). Detected host-side on the already-fetched
    token array, so the check costs zero extra device transfers."""

    def __init__(self, step: int, slots=()):
        slots = tuple(int(s) for s in slots)
        super().__init__(
            f"decode step {step} produced out-of-vocab tokens"
            + (f" in slots {list(slots)}" if slots else "")
        )
        self.step = step
        self.slots = slots


class EngineCrashError(ServeFailure):
    """The decode step itself died (XLA abort, chaos ``engine_crash``).

    Raised BEFORE the step runs, so the engine's host state still
    describes the pre-step world and ``snapshot()`` is valid."""

    def __init__(self, step: int):
        super().__init__(f"engine crash at decode step {step}")
        self.step = step


class HungStepError(ServeFailure):
    """A decode step outlived the watchdog's full escalation ladder
    (warn → dump → abort). Raised by the SUPERVISOR after the step
    finally returns (or is abandoned) — the hung thread itself cannot
    raise."""

    def __init__(self, elapsed_s: float):
        super().__init__(
            f"decode step hung for {elapsed_s:.1f}s (watchdog escalation "
            f"exhausted)"
        )
        self.elapsed_s = elapsed_s


class StepWatchdog:
    """Detect hung training steps from the host side.

    Usage::

        wd = StepWatchdog(timeout_s=300)
        for batch in loader:
            with wd.watch():
                state, metrics = train_step(state, *batch)
        wd.close()

    If a watched section outlives ``timeout_s`` the watchdog — on its own
    long-lived monitor thread, since the training thread is the one
    that's stuck — logs a critical message, dumps every thread's Python
    stack to stderr, and calls ``on_hang(elapsed_s)``. It fires at most
    once per watched section and never interrupts the training thread
    itself: detection, not preemption (in multi-host runs the callback
    should abort the process and let the coordination service restart
    the job).

    One monitor thread serves the whole run (arm/disarm just move a
    deadline under a condition variable — no per-step thread churn), and
    once ``disarm`` returns, no fire for that section can happen: the
    deadline check AND the report itself run under the lock, so a
    concurrent ``disarm`` either cancels the fire or blocks until the
    report finishes. The deadline is consumed BEFORE the report, so one
    expired section fires exactly once — re-arming during an in-flight
    ``_fire`` (the lock is re-entrant, so even a stage callback may
    re-arm) starts a NEW section and can never double-fire the old one.

    ``escalation`` graduates successive fires instead of the all-at-once
    legacy report: fire #n runs stage ``escalation[min(n-1, len-1)]`` —
    ``"warn"`` logs only, ``"dump"`` adds the stack/ring/flight
    post-mortem, ``"abort"`` additionally invokes ``on_hang`` (the
    process-abort callback in the engines). While stages remain, an
    expired section re-arms itself for another ``timeout_s`` — a
    persistently wedged step climbs the whole ladder with no help from
    the (blocked) training thread, and ``disarm`` still cancels at any
    rung. ``None`` keeps the legacy behavior: every fire warns, dumps,
    and calls ``on_hang``, exactly once per section.
    """

    STAGES = ("warn", "dump", "abort")

    def __init__(
        self,
        timeout_s: float,
        on_hang: Callable[[float], None] | None = None,
        dump_stacks: bool = True,
        metric_ring: Any | None = None,
        ring_tail: int = 32,
        flight_recorder: Any | None = None,
        escalation: tuple[str, ...] | None = None,
    ):
        if escalation is not None:
            escalation = tuple(escalation)
            bad = [s for s in escalation if s not in self.STAGES]
            if bad or not escalation:
                raise ValueError(
                    f"escalation stages must be drawn from {self.STAGES}, "
                    f"got {escalation!r}"
                )
        self.escalation = escalation
        self.last_stage: str | None = None  # stage of the newest fire
        self.timeout_s = timeout_s
        self.on_hang = on_hang
        self.dump_stacks = dump_stacks
        # Any object with .tail(n) -> list[dict] (obs.sinks.RingSink):
        # on firing, the last N step records are flushed to the log so
        # the operator sees what the run was doing when it wedged —
        # stacks say WHERE the host is stuck, the ring says WHAT the
        # training was converging (or not) toward.
        self.metric_ring = metric_ring
        self.ring_tail = ring_tail
        # obs.flight.FlightRecorder (anything with .dump(reason, **kw)):
        # adds the phase-timing tail and straggler stats to the report —
        # the ring says what the LOSS was doing, the flight recorder
        # says what the STEP TIMES were doing before the hang.
        self.flight_recorder = flight_recorder
        self.fired = 0  # total hang detections (for tests/metrics)
        self._log = get_logger()
        # Re-entrant lock: a stage callback (which runs inside _fire,
        # under the lock, on the monitor thread) may legitimately
        # re-arm for the next section without deadlocking.
        self._cv = threading.Condition(threading.RLock())
        self._deadline: float | None = None  # None = disarmed
        self._armed_timeout = timeout_s
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, timeout_s: float | None = None) -> None:
        """Start the countdown for one section; ``timeout_s`` overrides the
        default for sections with a different latency envelope (e.g. a
        checkpoint save)."""
        with self._cv:
            self._armed_timeout = timeout_s if timeout_s is not None else self.timeout_s
            self._deadline = time.monotonic() + self._armed_timeout
            self._cv.notify()

    def disarm(self) -> None:
        """The step completed in time; stop the countdown."""
        with self._cv:
            self._deadline = None
            self._cv.notify()

    @contextlib.contextmanager
    def watch(self):
        """Context manager: ``arm`` on enter, ``disarm`` on exit (also on
        exception paths)."""
        self.arm()
        try:
            yield self
        finally:
            self.disarm()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._deadline = None
            self._cv.notify()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if self._deadline is None:
                    self._cv.wait()
                    continue
                now = time.monotonic()
                remaining = self._deadline - now
                if remaining > 0:
                    self._cv.wait(timeout=remaining)
                    continue
                # Expired while still armed: consume the deadline (fire
                # once per section) and report WHILE HOLDING the lock, so
                # disarm() can never return with a fire still pending.
                elapsed = self._armed_timeout + (now - self._deadline)
                self._deadline = None
                self._fire(elapsed, self._armed_timeout)
                if (
                    self.escalation is not None
                    and self.fired < len(self.escalation)
                    and self._deadline is None
                    and not self._closed
                ):
                    # Ladder continuation: the hung thread cannot re-arm,
                    # so a still-wedged section escalates on its own —
                    # next stage after another timeout_s. disarm() (the
                    # section completed after all) cancels as usual; a
                    # stage callback that re-armed keeps ITS deadline.
                    self._deadline = (
                        time.monotonic() + self._armed_timeout
                    )

    def _fire(self, elapsed_s: float, timeout_s: float) -> None:
        self.fired += 1
        if self.escalation is None:
            stage = None  # legacy: warn + dump + callback, every fire
        else:
            stage = self.escalation[
                min(self.fired - 1, len(self.escalation) - 1)
            ]
        self.last_stage = stage
        do_dump = stage in (None, "dump", "abort")
        do_callback = stage in (None, "abort")
        self._log.critical(
            "watchdog: training step exceeded %.1fs (%.1fs elapsed) — host is "
            "likely blocked on a device transfer behind a hung collective"
            "%s",
            timeout_s,
            elapsed_s,
            "; dumping stacks" if do_dump else
            f" (escalation stage {stage!r}, fire #{self.fired})",
        )
        if not do_dump:
            return
        if self.dump_stacks:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        if self.metric_ring is not None:
            try:
                records = self.metric_ring.tail(self.ring_tail)
            except Exception as e:  # never let telemetry break the report
                self._log.critical("watchdog: metric ring unreadable: %r", e)
                records = []
            if records:
                self._log.critical(
                    "watchdog: last %d metric records before hang:", len(records)
                )
                for rec in records:
                    self._log.critical("watchdog:   %s", json.dumps(rec, default=str))
        if self.flight_recorder is not None:
            try:
                self.flight_recorder.dump(
                    "watchdog", elapsed_s=elapsed_s, timeout_s=timeout_s
                )
            except Exception as e:  # never let telemetry break the report
                self._log.critical("watchdog: flight recorder dump failed: %r", e)
        if do_callback and self.on_hang is not None:
            self.on_hang(elapsed_s)


def _identity_fields() -> dict[str, int]:
    """``process_id``/``generation`` stamps for event records, so a
    multi-process recovery timeline is attributable per rank (merged
    JSONL streams are otherwise ambiguous the moment a second rank
    writes). Resolved lazily through ``parallel/multihost.py`` — the
    labels re-resolve after each ``jax.distributed`` re-initialization,
    never touching an uninitialized jax backend."""
    try:
        from cs744_pytorch_distributed_tutorial_tpu.parallel.multihost import (
            runtime_labels,
        )

        labels = runtime_labels()
        return {
            "process_id": labels["process_id"],
            "generation": labels["generation"],
        }
    except Exception:  # identity stamping must never break recovery
        return {}


def emit_event(target: Any, event: str, **fields: Any) -> None:
    """Put one ``kind:"event"`` record on ``target``: either a
    ``Telemetry`` (``obs/metrics.py``, has ``emit_event``) or a raw sink
    (``obs/sinks.py``, has ``emit``). None is a no-op — recovery never
    depends on telemetry being configured. Every record is stamped with
    ``process_id``/``generation`` (explicit fields win)."""
    if target is None:
        return
    fields = {**_identity_fields(), **fields}
    if hasattr(target, "emit_event"):
        target.emit_event(event, **fields)
    else:
        target.emit(
            {"kind": "event", "event": event, "time": time.time(), **fields}
        )


def run_with_recovery(
    trainer: Any,
    *,
    max_restarts: int = 2,
    fit_args: tuple = (),
    fit_kwargs: dict[str, Any] | None = None,
    backoff_s: float = 0.0,
    backoff_factor: float = 2.0,
    max_backoff_s: float = 60.0,
    backoff_jitter: str = "none",
    jitter_seed: int | None = None,
    jitter_rng: Any = None,
    sleep: Callable[[float], None] = time.sleep,
    telemetry: Any = None,
    remesh: Callable[[Any, TrainingFailure], Any] | None = None,
):
    """Run ``trainer.fit`` with restart recovery and a graduated
    escalation ladder.

    On a ``TrainingFailure`` (e.g. ``NonFiniteLossError``) the run is
    restarted: ``fit`` restores the newest recoverable state and resumes
    at the recorded step, so work since that state — including the steps
    that produced the divergence — is replayed from known-good state.
    The restore tier is ``fit``'s arbitration: the in-memory replicated
    snapshot (``trainer.memstore``, zero filesystem reads) when it is at
    least as new as the newest disk checkpoint, else the disk
    checkpoint. Requires at least one tier —
    ``trainer.cfg.checkpoint_dir`` or a ``trainer.memstore`` (without
    either there is nothing to restart FROM, and the failure re-raises
    immediately).

    ``backoff_s`` arms exponential backoff between restarts (attempt n
    sleeps ``backoff_s * backoff_factor**(n-1)``, capped at
    ``max_backoff_s``) — in a real deployment the fault is usually
    environmental and hammering the restart path makes it worse.
    ``sleep`` is injectable for tests.

    ``backoff_jitter="decorrelated"`` switches to decorrelated jitter
    (attempt n sleeps ``uniform(backoff_s, prev * 3)``, capped at
    ``max_backoff_s``): after a process loss, N surviving ranks all
    restart at once, and deterministic exponential backoff keeps them in
    lockstep — every survivor hammers the re-elected coordinator at the
    same instant, every attempt. The jitter stream is seeded per
    ``(jitter_seed, process_id, generation)`` so each rank draws a
    DIFFERENT (but reproducible) sequence; pass ``jitter_rng`` to inject
    the generator directly in tests. The default ``"none"`` keeps the
    deterministic schedule bit-for-bit.

    A ``DeviceLossError`` escalates past retry: when ``remesh`` is
    given (``parallel/elastic.py::default_remesh``), it is called as
    ``remesh(trainer, failure)`` and must return a NEW trainer on the
    surviving mesh (carrying the memstore over, so the next ``fit``
    reshards the snapshot onto the new world). Without ``remesh`` the
    device loss restarts on the old mesh and will typically fail again
    until ``max_restarts`` gives up.

    Every transition emits a ``kind:"event"`` record on ``telemetry``
    (a ``Telemetry`` or raw obs sink): ``recovery_restart`` per attempt
    (with tier/backoff/failure), ``recovery_remesh`` on re-mesh,
    ``recovery_complete`` / ``recovery_giveup`` at the end.

    Works with either engine — the CIFAR ``Trainer`` (``fit()`` ->
    ``(state, history)``) or ``LMTrainer`` (``fit(tokens, steps)`` ->
    ``(params, opt_state, losses)``): returns ``fit``'s tuple with
    ``restarts`` appended.
    """
    log = get_logger()
    if not (
        getattr(trainer.cfg, "checkpoint_dir", None)
        or getattr(trainer, "memstore", None) is not None
    ):
        raise ValueError(
            "run_with_recovery needs cfg.checkpoint_dir or an in-memory "
            "snapshot tier (trainer.memstore): restart-based recovery "
            "resumes from the newest recoverable state"
        )
    if backoff_jitter not in ("none", "decorrelated"):
        raise ValueError(
            f'backoff_jitter must be "none" or "decorrelated", '
            f"got {backoff_jitter!r}"
        )
    rng = jitter_rng
    if backoff_jitter == "decorrelated" and rng is None:
        identity = _identity_fields()
        rng = np.random.default_rng(
            (
                0 if jitter_seed is None else int(jitter_seed),
                identity.get("process_id", 0),
                identity.get("generation", 0),
            )
        )
    prev_delay = backoff_s
    kwargs = fit_kwargs or {}
    restarts = 0
    while True:
        try:
            result = trainer.fit(*fit_args, **kwargs)
            if restarts:
                emit_event(
                    telemetry, "recovery_complete", restarts=restarts
                )
            return (*result, restarts)
        except TrainingFailure as e:
            restarts += 1
            if restarts > max_restarts:
                emit_event(
                    telemetry,
                    "recovery_giveup",
                    restarts=restarts - 1,
                    failure=repr(e),
                    # The full traceback string, not just repr(e): a
                    # giveup is the record the operator debugs FROM, and
                    # by then the process that could re-raise is gone.
                    traceback="".join(_traceback.format_exception(e)),
                )
                log.critical(
                    "giving up after %d restarts (last failure: %s)", restarts - 1, e
                )
                raise
            delay = 0.0
            if backoff_s > 0:
                if backoff_jitter == "decorrelated":
                    delay = min(
                        float(
                            rng.uniform(
                                backoff_s, max(backoff_s, prev_delay * 3.0)
                            )
                        ),
                        max_backoff_s,
                    )
                    prev_delay = delay
                else:
                    delay = min(
                        backoff_s * backoff_factor ** (restarts - 1),
                        max_backoff_s,
                    )
            tier = "restart"
            if isinstance(e, DeviceLossError) and remesh is not None:
                old_world = int(
                    getattr(trainer, "mesh").devices.size
                    if getattr(trainer, "mesh", None) is not None
                    else 0
                )
                trainer = remesh(trainer, e)
                new_world = int(
                    getattr(trainer, "mesh").devices.size
                    if getattr(trainer, "mesh", None) is not None
                    else 0
                )
                tier = "remesh"
                emit_event(
                    telemetry,
                    "recovery_remesh",
                    old_world=old_world,
                    new_world=new_world,
                    lost=list(e.lost),
                )
                log.error(
                    "device loss (%s): re-meshed %d -> %d devices",
                    e,
                    old_world,
                    new_world,
                )
            emit_event(
                telemetry,
                "recovery_restart",
                restart=restarts,
                max_restarts=max_restarts,
                failure=repr(e),
                tier=tier,
                backoff_s=delay,
            )
            log.error(
                "training failure (%s); restart %d/%d from newest "
                "recoverable state (backoff %.1fs)",
                e,
                restarts,
                max_restarts,
                delay,
            )
            if delay > 0:
                sleep(delay)
