"""Replicated in-memory snapshots — recovery with zero filesystem reads.

``ReplicatedSnapshot`` keeps the last K *committed* training-state
pytrees (``TrainState`` / ``LMState``) as host-RAM copies. The engines
feed it through the same divergence-safe pending/certify machinery as
the disk ``Checkpointer`` (a snapshot is taken only once a later finite
loss certifies its params), so a restore can never hand back a state
whose own forward pass diverged.

Why a second tier above Orbax: restart-from-disk recovery pays
serialization, directory fencing, and a full read back — for the common
transient failures (a flaky NaN, a wedged step the watchdog aborted, a
SIGTERM that the harness converted to a restart) the state that was
just live in HBM is still byte-identical in host RAM. ``restore_latest``
here performs **zero filesystem reads** (asserted by tests/test_chaos.py
via the instrumented ``Checkpointer`` counters) and reuses the disk
checkpointer's exact placement discipline
(``utils/checkpoint.py::adapt_and_place``): leaves are elastically
resized (leading world-size axis slice/tile, with the same ``adapt``
hook the ZeRO engines use to re-chunk flat shard state) and committed
to the template's shardings, so a snapshot taken on an N-device mesh
restores onto an M-device survivor mesh (``parallel/elastic.py``).

``save`` issues the device->host copies for every leaf asynchronously
first, then gathers — transfers overlap across leaves, and the gathered
copies are independent of the live buffers, so the train loop may
immediately donate them to the next step.

Single-host by design: the replicated copy lives in THIS process's RAM.
Multi-host deployments pair it with the disk tier (every host snapshots
its addressable shards; a lost host falls through to disk).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from cs744_pytorch_distributed_tutorial_tpu.utils.checkpoint import (
    adapt_and_place,
)


class ReplicatedSnapshot:
    """Ring of the last ``max_to_keep`` committed state pytrees, keyed
    by training step, entirely in host RAM."""

    def __init__(self, max_to_keep: int = 2):
        if max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        self.max_to_keep = max_to_keep
        self._ring: dict[int, Any] = {}  # step -> host pytree
        self.saves = 0
        self.restores = 0

    def save(self, state: Any, *, step: int | None = None) -> int:
        """Snapshot ``state`` to host RAM, keyed by ``step`` (default:
        the pytree's own ``.step``). Returns the key. Re-saving a step
        overwrites it; the ring keeps the newest ``max_to_keep`` steps."""
        leaves = jax.tree_util.tree_leaves(state)
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                if not leaf.is_fully_addressable:
                    raise ValueError(
                        "ReplicatedSnapshot is single-host: a leaf spans "
                        "processes; snapshot on a host-local mesh or use "
                        "the disk Checkpointer for this state"
                    )
                # Start every device->host transfer before blocking on
                # any single one — the copies land in parallel.
                leaf.copy_to_host_async()
        host = jax.tree.map(
            lambda l: np.asarray(jax.device_get(l))
            if isinstance(l, jax.Array)
            else l,
            state,
        )
        if step is None:
            step = int(np.asarray(host.step))
        self._ring[step] = host
        while len(self._ring) > self.max_to_keep:
            del self._ring[min(self._ring)]
        self.saves += 1
        return step

    def steps(self) -> list[int]:
        return sorted(self._ring)

    def latest_step(self) -> int | None:
        return max(self._ring) if self._ring else None

    def restore_latest(self, template: Any, adapt=None) -> Any | None:
        """Rebuild the newest snapshot onto ``template``'s structure and
        shardings; None when empty. Mesh-elastic with the Checkpointer's
        exact semantics — same leading-axis slice/tile, same ``adapt``
        hook for re-chunking ZeRO shard state, same committed
        ``device_put`` placement (donation-pairing safety). No
        filesystem access on any path."""
        step = self.latest_step()
        if step is None:
            return None
        self.restores += 1
        return adapt_and_place(self._ring[step], template, adapt)

    def clear(self) -> None:
        self._ring.clear()
