"""Replica-divergence detection — the SPMD answer to race detection.

The reference avoids data races by construction: every collective is
synchronous (``async_op=False`` at ``master/part2a/part2a.py:44,52``;
immediate ``req.wait()`` on each p2p op,
``master/part2a/part2a_extra.py:45-58``) — but nothing ever *verifies*
that the four ranks' parameters stayed in lockstep (SURVEY §5.2). In
SPMD the analogous failure is replica divergence: a wrong or missing
gradient sync leaves each device training its own drifting model while
every step "succeeds" (exactly the bug class the LM engine's
``check_vma=False`` pitfall produces — see ``train/lm.py``).

``DivergenceMonitor`` detects it at run time: a ``jax.debug.callback``
inside the jitted step streams a per-replica checksum of the synced
gradients to the host, where the monitor compares replicas per step.
Cost is one scalar per replica per step; enable with
``TrainConfig(debug_sync_check=True)`` — the Trainer then checks the
monitor at each epoch boundary and raises on divergence.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp


def tree_checksum(tree) -> jax.Array:
    """Order-stable scalar fingerprint of a pytree: sum of per-leaf L1
    norms. Identical synced gradients => identical checksums; any
    per-replica drift shows up after a step or two."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.abs(leaf.astype(jnp.float32)).sum() for leaf in leaves)


class DivergenceMonitor:
    """Streams (step, replica, checksum) records; flags disagreement.

    Divergence is evaluated incrementally on ``record`` against the
    step's first-seen replica, so memory stays bounded: per-step records
    older than ``window`` steps are pruned (divergent step ids are kept
    forever — they are the findings). Thread-safe: ``jax.debug.callback``
    may fire from runtime threads.
    """

    def __init__(self, rtol: float = 1e-6, window: int = 4096):
        self.rtol = rtol
        self.window = window
        self._lock = threading.Lock()
        self._records: OrderedDict[int, dict[int, float]] = OrderedDict()
        self._divergent: set[int] = set()
        self._steps_seen = 0

    def record(self, step: int, replica: int, checksum: float) -> None:
        step, replica, checksum = int(step), int(replica), float(checksum)
        with self._lock:
            by_replica = self._records.get(step)
            if by_replica is None:
                by_replica = self._records[step] = {}
                self._steps_seen += 1
                while len(self._records) > self.window:
                    self._records.popitem(last=False)
            if not math.isfinite(checksum):
                self._divergent.add(step)
            elif by_replica:
                ref = next(iter(by_replica.values()))
                if abs(checksum - ref) > self.rtol * max(abs(ref), 1.0):
                    self._divergent.add(step)
            by_replica[replica] = checksum

    def callback(self, step, replica, checksum) -> None:
        """Signature taken by ``jax.debug.callback`` inside the step."""
        self.record(step, replica, checksum)

    @staticmethod
    def flush() -> None:
        """Wait for in-flight debug callbacks: delivery is asynchronous,
        so checks must fence first or they miss the most recent steps."""
        jax.effects_barrier()

    @property
    def steps_recorded(self) -> int:
        self.flush()
        with self._lock:
            return self._steps_seen

    def replicas_seen(self, step: int) -> int:
        self.flush()
        with self._lock:
            return len(self._records.get(int(step), ()))

    def divergent_steps(self) -> list[int]:
        """Steps where any replica disagreed beyond rtol or reported a
        non-finite checksum."""
        self.flush()
        with self._lock:
            return sorted(self._divergent)

    def assert_in_sync(self) -> None:
        bad = self.divergent_steps()
        if bad:
            raise AssertionError(
                f"replica divergence detected at steps {bad[:10]}"
                + ("..." if len(bad) > 10 else "")
                + " — gradient sync is broken or numerics are non-finite"
            )
